"""LLMPlanner: prompt construction, endpoint resolution, retry/fallback
(SURVEY.md §7 step 6; fixes reference bugs B6/B7/B9)."""

import asyncio

import pytest

from mcpx.core.config import MCPXConfig, PlannerConfig
from mcpx.models.tokenizer import ByteTokenizer
from mcpx.planner.base import PlanContext
from mcpx.planner.llm import LLMPlanner
from mcpx.registry.base import ServiceRecord
from mcpx.registry.memory import InMemoryRegistry
from mcpx.telemetry.stats import ServiceStats


class FakeEngine:
    """Duck-typed engine returning scripted completions."""

    def __init__(self, outputs):
        self.outputs = list(outputs)
        self.tokenizer = ByteTokenizer()
        self.state = "ready"
        self.prompts = []

    async def start(self):
        self.state = "ready"

    async def generate(self, prompt_ids, **kw):
        import dataclasses

        self.prompts.append(self.tokenizer.decode(prompt_ids))

        @dataclasses.dataclass
        class R:
            text: str

        return R(text=self.outputs.pop(0) if self.outputs else "")


async def _registry():
    reg = InMemoryRegistry()
    await reg.put(
        ServiceRecord(
            name="fetch",
            endpoint="http://svc/fetch",
            description="fetch data",
            output_schema={"data": "str"},
            fallbacks=["http://backup/fetch"],
        )
    )
    await reg.put(
        ServiceRecord(
            name="summarize",
            endpoint="http://svc/sum",
            description="summarize text",
            input_schema={"data": "str"},
            cost_profile={"cost": 2.0},
        )
    )
    return reg


GOOD = '{"steps":[{"s":"fetch","in":[],"next":["summarize"]},{"s":"summarize","in":["data"],"next":[]}]}'


def test_valid_completion_resolves_endpoints_from_registry():
    async def go():
        reg = await _registry()
        eng = FakeEngine([GOOD])
        p = LLMPlanner(eng, PlannerConfig(kind="llm"))
        plan = await p.plan("fetch and summarize", PlanContext(registry=reg))
        assert [n.name for n in plan.nodes] == ["fetch", "summarize"]
        # Endpoints come from the registry, never from model output.
        assert plan.node("fetch").endpoint == "http://svc/fetch"
        assert plan.node("fetch").fallbacks == ["http://backup/fetch"]
        assert plan.node("summarize").endpoint == "http://svc/sum"
        assert len(plan.edges) == 1 and plan.edges[0].src == "fetch"
        assert "LLM-planned" in plan.explanation

    asyncio.run(go())


def test_unknown_service_retries_then_falls_back_to_heuristic():
    async def go():
        reg = await _registry()
        bad = '{"steps":[{"s":"nonexistent","in":[],"next":[]}]}'
        eng = FakeEngine([bad, bad, bad])
        p = LLMPlanner(eng, PlannerConfig(kind="llm", max_plan_retries=2))
        plan = await p.plan("summarize the data", PlanContext(registry=reg))
        assert len(eng.prompts) == 3  # exhausted retry budget
        assert plan.nodes  # heuristic fallback produced something real
        assert all(n.service in ("fetch", "summarize") for n in plan.nodes)
        assert "heuristic fallback" in plan.explanation

    asyncio.run(go())


def test_second_attempt_can_succeed():
    async def go():
        reg = await _registry()
        eng = FakeEngine(['{"steps":[{"s":"ghost","in":[],"next":[]}]}', GOOD])
        p = LLMPlanner(eng, PlannerConfig(kind="llm", max_plan_retries=2))
        plan = await p.plan("x", PlanContext(registry=reg))
        assert [n.name for n in plan.nodes] == ["fetch", "summarize"]
        assert "attempt 2" in plan.explanation

    asyncio.run(go())


def test_prompt_contains_telemetry_and_respects_shortlist_and_budget():
    async def go():
        reg = await _registry()
        for i in range(40):
            await reg.put(
                ServiceRecord(name=f"f{i}", endpoint=f"http://x/{i}", description="y" * 40)
            )
        eng = FakeEngine([GOOD])
        p = LLMPlanner(eng, PlannerConfig(kind="llm", max_prompt_tokens=600))
        ctx = PlanContext(
            registry=reg,
            telemetry={"fetch": ServiceStats("fetch", ewma_latency_ms=12.5, ewma_error_rate=0.25)},
            shortlist=["summarize", "fetch"],
        )
        await p.plan("fetch and summarize", ctx)
        prompt = eng.prompts[0]
        assert len(prompt) <= 600
        assert "err=0.25" in prompt
        assert "p50=12" in prompt or "p50=13" in prompt
        assert "c=2" in prompt
        # Shortlisted services only, in retrieval order.
        assert "\nsummarize in:" in prompt and "\nf3 in:" not in prompt
        assert prompt.index("\nsummarize in:") < prompt.index("\nfetch in:")
        assert prompt.rstrip().endswith("JSON:")
        assert "fetch and summarize" in prompt

    asyncio.run(go())


def test_exclude_removes_candidates():
    async def go():
        reg = await _registry()
        eng = FakeEngine([GOOD, GOOD])
        p = LLMPlanner(eng, PlannerConfig(kind="llm", max_plan_retries=0))
        ctx = PlanContext(registry=reg, exclude={"fetch"})
        # GOOD names "fetch", which is excluded -> unknown -> heuristic fallback.
        plan = await p.plan("summarize", ctx)
        assert all(n.service != "fetch" for n in plan.nodes)

    asyncio.run(go())


def test_model_in_the_loop_shape_only_grammar_falls_back_cleanly():
    """Real engine, random weights, constrain_names=off (round-1 behavior):
    constrained decode yields grammar-valid JSON whose service names are
    garbage -> planner must land on the heuristic fallback without ever
    raising a parse error (bug B7 fixed)."""
    from mcpx.engine.engine import InferenceEngine

    async def go():
        cfg = MCPXConfig.from_dict(
            {
                "model": {"size": "test", "max_seq_len": 256},
                "engine": {
                    "use_pallas": False,
                    "max_batch_size": 2,
                    "max_decode_len": 64,
                    "max_pages_per_seq": 16,
                    "temperature": 0.0,
                },
                "planner": {"kind": "llm", "max_plan_retries": 1, "constrain_names": "off"},
            }
        )
        eng = InferenceEngine(cfg)
        p = LLMPlanner(eng, cfg.planner)
        try:
            reg = await _registry()
            plan = await p.plan("fetch then summarize", PlanContext(registry=reg))
            assert plan.nodes
            plan.validate()
        finally:
            await eng.aclose()

    asyncio.run(go())


@pytest.mark.parametrize("mode", ["registry", "shortlist"])
def test_model_in_the_loop_trie_grammar_accepts_llm_plan(mode):
    """Real engine, random weights, trie-constrained names (VERDICT r1 #2):
    the model CANNOT emit an unknown service, so even noise-weight decodes
    produce accepted LLM plans — origin stays 'llm', no heuristic fallback,
    and every node resolves to a registry endpoint."""
    from mcpx.engine.engine import InferenceEngine

    async def go():
        cfg = MCPXConfig.from_dict(
            {
                "model": {"size": "test", "max_seq_len": 256},
                "engine": {
                    "use_pallas": False,
                    "max_batch_size": 2,
                    "max_decode_len": 96,
                    "max_pages_per_seq": 16,
                    "temperature": 0.0,
                },
                "planner": {
                    "kind": "llm",
                    "max_plan_retries": 0,
                    "constrain_names": mode,
                },
            }
        )
        eng = InferenceEngine(cfg)
        p = LLMPlanner(eng, cfg.planner)
        try:
            reg = await _registry()
            ctx = PlanContext(
                registry=reg,
                shortlist=["fetch", "summarize"] if mode == "shortlist" else None,
            )
            plan = await p.plan("fetch then summarize", ctx)
            assert plan.origin == "llm", plan.explanation
            assert plan.nodes
            for n in plan.nodes:
                assert n.service in ("fetch", "summarize")
                assert n.endpoint.startswith("http://svc/")
            plan.validate()
        finally:
            await eng.aclose()

    asyncio.run(go())


def test_grammar_cache_identity_per_registry_version():
    """Concurrent plans against one registry version must share ONE grammar
    object (engine batches by grammar identity); a registry mutation bumps
    the version and yields a fresh grammar."""

    async def go():
        reg = await _registry()
        eng = FakeEngine([GOOD] * 4)
        p = LLMPlanner(eng, PlannerConfig(kind="llm"))
        v = await reg.version()
        ctx = PlanContext(registry=reg, registry_version=v)
        recs = await reg.list_services()
        g1, g2 = await asyncio.gather(p._grammar(ctx, v, recs), p._grammar(ctx, v, recs))
        assert g1 is g2
        assert g1 is not None and g1.service_names == ("fetch", "summarize")
        await reg.put(ServiceRecord(name="extra", endpoint="http://svc/extra"))
        v2 = await reg.version()
        assert v2 != v
        ctx2 = PlanContext(registry=reg, registry_version=v2)
        recs2 = await reg.list_services()
        g3 = await p._grammar(ctx2, v2, recs2)
        assert g3 is not g1
        assert g3.service_names is not None and "extra" in g3.service_names

    asyncio.run(go())


def test_grammar_ladder_keys_first_then_free_then_shape():
    """_build_grammar tries key tries first (constrain_input_keys default),
    falls back to free keys, then shape-only — each transition observable."""

    async def go():
        reg = await _registry()
        _, services = await __import__("mcpx.registry.base", fromlist=["stable_snapshot"]).stable_snapshot(reg)
        p = LLMPlanner(FakeEngine([]), PlannerConfig(kind="llm"))
        g = p._build_grammar(["fetch", "summarize"], services)
        assert g is not None
        # Key tries took effect: a plan using a schema key is accepted...
        ok = '{"steps":[{"s":"fetch","in":["data"],"next":[]}]}'
        assert g.is_accept(g.walk(ok))
        # ...while an out-of-schema key is UNREPRESENTABLE.
        bad = '{"steps":[{"s":"fetch","in":["nope"],"next":[]}]}'
        assert g.walk(bad) == g.dead_state

        # With constrain_input_keys=off, free-string keys are accepted.
        p2 = LLMPlanner(FakeEngine([]), PlannerConfig(kind="llm", constrain_input_keys="off"))
        g2 = p2._build_grammar(["fetch", "summarize"], services)
        assert g2.walk(bad) != g2.dead_state

    asyncio.run(go())


def test_exclude_builds_grammar_without_excluded_name():
    """Replan exclusions leave the trie (not just the resolution map):
    an excluded service's name becomes unrepresentable."""

    async def go():
        reg = await _registry()
        from mcpx.registry.base import stable_snapshot

        version, services = await stable_snapshot(reg)
        p = LLMPlanner(FakeEngine([]), PlannerConfig(kind="llm"))
        ctx = PlanContext(registry=reg, exclude={"fetch"}, registry_version=version)
        g = await p._grammar(ctx, version, services)
        assert g is not None
        assert g.walk('{"steps":[{"s":"summarize","in":[],"next":[]}]}') != g.dead_state
        assert g.walk('{"steps":[{"s":"fetch","in":[],"next":[]}]}') == g.dead_state
        # Cache key includes the exclude set: a no-exclude context gets a
        # different grammar that still accepts "fetch".
        ctx2 = PlanContext(registry=reg, registry_version=version)
        g2 = await p._grammar(ctx2, version, services)
        assert g2 is not g
        assert g2.walk('{"steps":[{"s":"fetch","in":[],"next":[]}]}') != g2.dead_state

    asyncio.run(go())


def test_warm_runs_one_generate_through_registry_grammar():
    async def go():
        reg = await _registry()
        eng = FakeEngine(["x"])
        p = LLMPlanner(eng, PlannerConfig(kind="llm"))
        await p.warm(reg)
        # One generate went through with the registry grammar attached.
        assert len(eng.prompts) == 1
        # Empty registry: warm is a no-op, not an error.
        empty = InMemoryRegistry()
        await p.warm(empty)
        assert len(eng.prompts) == 1

    asyncio.run(go())


def test_repair_prunes_dangling_and_backward_next():
    """Grammar-valid decodes whose 'next' references name un-emitted or
    earlier steps are REPAIRED (forward edges to kept steps only) instead of
    discarded to the heuristic — the main fallback cause at 1k-service
    registries (trie guarantees registry membership, not step membership)."""

    async def go():
        reg = await _registry()
        # "ghost" exists in the registry? No — but repair drops the EDGE, not
        # the step; both steps exist in the registry here while "next" points
        # at an un-emitted service and backwards.
        wire = (
            '{"steps":['
            '{"s":"fetch","in":[],"next":["summarize","fetch"]},'
            '{"s":"summarize","in":["data"],"next":["fetch"]},'
            '{"s":"summarize","in":[],"next":[]}'
            "]}"
        )
        eng = FakeEngine([wire])
        p = LLMPlanner(eng, PlannerConfig(kind="llm", max_plan_retries=0))
        plan = await p.plan("x", PlanContext(registry=reg))
        assert plan.origin == "llm"
        assert [n.name for n in plan.nodes] == ["fetch", "summarize"]  # dup dropped
        assert len(plan.edges) == 1  # forward fetch->summarize only
        assert plan.edges[0].src == "fetch" and plan.edges[0].dst == "summarize"
        assert "repaired" in plan.explanation

    asyncio.run(go())


def test_normalize_dataflow_rewires_and_prunes():
    """The planner turns an LLM plan's declared topology into real
    dataflow: step-wire inputs arrive as {key: key} (payload-only under the
    executor's name-keyed results), so overlapping keys along emitted edges
    are rewired to read the upstream node's result; an edge left carrying
    no data after rewiring is pruned (flag-disable restores it)."""

    async def go():
        reg = await _registry()
        await reg.put(
            ServiceRecord(
                name="audit",
                endpoint="http://svc/audit",
                description="audit the request",
                input_schema={"query": "str"},  # nothing produces "query"
            )
        )
        wire = (
            '{"steps":['
            '{"s":"fetch","in":[],"next":["summarize","audit"]},'
            '{"s":"summarize","in":["data"],"next":[]},'
            '{"s":"audit","in":["query"],"next":[]}'
            "]}"
        )
        p = LLMPlanner(
            FakeEngine([wire]), PlannerConfig(kind="llm", max_plan_retries=0)
        )
        plan = await p.plan("x", PlanContext(registry=reg))
        assert plan.origin == "llm"
        assert [(e.src, e.dst) for e in plan.edges] == [("fetch", "summarize")]
        assert "1 dataflow-free edge(s) pruned" in plan.explanation
        # The surviving edge now MOVES data: summarize reads fetch's result
        # (executor results are keyed by node name), not payload["data"].
        assert plan.node("summarize").inputs == {"data": "fetch"}
        # audit keeps its payload wiring and is a parallel root, not
        # serialized behind a service it shares nothing with.
        assert plan.node("audit").inputs == {"query": "query"}
        assert plan.topological_generations()[0] == sorted(["fetch", "audit"])

        p_off = LLMPlanner(
            FakeEngine([wire]),
            PlannerConfig(
                kind="llm", max_plan_retries=0, prune_dataflow_free_edges=False
            ),
        )
        plan_off = await p_off.plan("x", PlanContext(registry=reg))
        assert len(plan_off.edges) == 2
        # Rewiring happens regardless of the prune flag.
        assert plan_off.node("summarize").inputs == {"data": "fetch"}

    asyncio.run(go())


def test_token_exact_clamp_packs_subword_prompts():
    """With a subword vocab the clamp is token-exact: the prompt may exceed
    the budget in CHARS (impossible under the old 1-char=1-token clamp) while
    its encoding stays within the token budget, so shortlist lines that a
    char clamp would drop survive."""

    async def go():
        from mcpx.models.tokenizer import make_tokenizer

        reg = await _registry()
        for i in range(30):
            await reg.put(
                ServiceRecord(
                    name=f"catalog-fetch-{i:04d}",
                    endpoint=f"http://x/{i}",
                    input_schema={"query": "str", "user_id": "str"},
                    output_schema={"status": "str"},
                )
            )
        eng = FakeEngine([GOOD])
        eng.tokenizer = make_tokenizer("bpe")
        budget = 160
        p = LLMPlanner(eng, PlannerConfig(kind="llm", max_prompt_tokens=budget))
        ctx = PlanContext(
            registry=reg,
            shortlist=[f"catalog-fetch-{i:04d}" for i in range(30)],
        )
        await p.plan("fetch the catalog things", ctx)
        prompt = eng.prompts[0]
        n_tokens = len(eng.tokenizer.encode(prompt))
        assert n_tokens <= budget, n_tokens
        assert len(prompt) > budget  # chars exceed the token budget: packed
        assert prompt.count("\ncatalog-fetch-") >= 8  # far more than a char clamp keeps
        assert prompt.rstrip().endswith("JSON:")
        assert "fetch the catalog things" in prompt

    asyncio.run(go())


def test_typed_dataflow_size_gate_is_observable():
    """constrain_dataflow=True with a shortlist wider than the 24-service
    typed gate must NOT silently serve an untyped grammar: the typed_off
    fallback counter and a warning record that the dataflow guarantee is
    off (same observability contract as a failed typed build)."""

    async def go():
        from mcpx.telemetry.metrics import Metrics

        reg = InMemoryRegistry()
        for i in range(30):
            await reg.put(
                ServiceRecord(
                    name=f"svc-{i:04d}",
                    endpoint=f"http://x/{i}",
                    input_schema={"query": "str"},
                    output_schema={"status": "str"},
                )
            )
        from mcpx.registry.base import stable_snapshot

        version, services = await stable_snapshot(reg)
        eng = FakeEngine([])
        eng.metrics = Metrics()
        p = LLMPlanner(eng, PlannerConfig(kind="llm", constrain_names="shortlist"))

        def typed_off():
            return eng.metrics.grammar_fallbacks.labels(kind="typed_off")._value.get()

        before = typed_off()
        g = p._build_grammar(
            [s.name for s in services], services, version=version, typed=True
        )
        assert g is not None
        assert typed_off() == before + 1

        # Within the gate: no typed_off increment.
        g2 = p._build_grammar(
            [s.name for s in services[:8]], services[:8], version=version, typed=True
        )
        assert g2 is not None
        assert typed_off() == before + 1

    asyncio.run(go())
