"""Scheduler integration through the real HTTP surface: 429s carry
Retry-After, the degradation ladder tags responses ``planner: "degraded"``,
and with the scheduler disabled the /plan path is byte-identical to the
pass-through behavior (no ``planner`` field at all)."""

import asyncio

from mcpx.core.config import MCPXConfig
from mcpx.core.dag import Plan
from mcpx.registry.base import ServiceRecord
from mcpx.server.app import build_app
from mcpx.server.factory import build_control_plane

from tests.test_server import with_client


class SlowPlanner:
    """Mock primary planner with a fixed service delay — stands in for the
    LLM under overload (build_app never learns the difference)."""

    def __init__(self, delay_s: float) -> None:
        self.delay_s = delay_s
        self.calls = 0

    async def plan(self, intent: str, context) -> Plan:
        self.calls += 1
        await asyncio.sleep(self.delay_s)
        from mcpx.core.dag import DagNode

        p = Plan(
            nodes=[DagNode(name="svc-a", service="svc-a", endpoint="local://svc-a")],
            edges=[],
            intent=intent,
        )
        p.origin = "llm"
        return p


def _cp(scheduler_cfg: dict, delay_s: float):
    cfg = MCPXConfig.from_dict(
        {"scheduler": scheduler_cfg, "retrieval": {"enabled": False}}
    )
    planner = SlowPlanner(delay_s)
    cp = build_control_plane(cfg, planner=planner)
    return cp, planner


def _seed(cp):
    # The degraded path plans heuristically over the registry — it needs a
    # real service to chain.
    return cp.registry.put(
        ServiceRecord(
            name="svc-a",
            endpoint="local://svc-a",
            description="plan anything about svc",
            input_schema={"q": "str"},
            output_schema={"r": "str"},
        )
    )


def test_queue_full_sheds_429_with_retry_after():
    async def go():
        cp, planner = _cp(
            {
                "enabled": True,
                "max_parallel": 1,
                "max_queue_depth": 1,
                "default_deadline_ms": 0,  # no deadlines: isolate the queue cap
            },
            delay_s=0.3,
        )
        await _seed(cp)

        async def drive(client):
            async def one(delay):
                await asyncio.sleep(delay)
                r = await client.post("/plan", json={"intent": "plan svc"})
                return r

            # Staggered so arrival order is deterministic: r1 dispatches,
            # r2 queues (depth = cap), r3 sheds.
            rs = await asyncio.gather(one(0.0), one(0.05), one(0.1))
            statuses = [r.status for r in rs]
            assert sorted(statuses) == [200, 200, 429], statuses
            shed = rs[statuses.index(429)]
            assert int(shed.headers["Retry-After"]) >= 1
            body = await shed.json()
            assert "admission refused" in body["error"]
            ok = rs[statuses.index(200)]
            ok_body = await ok.json()
            # Scheduler on, ladder not engaged: primary tier, tagged.
            assert ok_body["planner"] == "primary"
            assert ok_body["origin"] == "llm"
            # Shed decisions are visible on /metrics.
            m = await (await client.get("/metrics")).text()
            assert 'mcpx_sched_decisions_total{outcome="shed_queue"}' in m

        await with_client(build_app(cp), drive)

    asyncio.run(go())


def test_sustained_overload_degrades_to_shortlist_planner_and_tags():
    async def go():
        cp, planner = _cp(
            {
                "enabled": True,
                "max_parallel": 1,
                "default_deadline_ms": 0,
                "slo_ms": 20.0,  # 10 ms queue-wait EWMA engages the ladder
                "degrade_threshold": 0.5,
                "recover_threshold": 0.25,
                "degrade_min_hold_s": 60.0,  # no mid-test recovery
            },
            delay_s=0.25,
        )
        await _seed(cp)

        async def drive(client):
            async def one(delay, i):
                # Distinct intents: a shared intent would let the degraded
                # tier answer from the plan cache (by design) and mask the
                # heuristic path this test exercises.
                await asyncio.sleep(delay)
                r = await client.post("/plan", json={"intent": f"plan svc {i}"})
                return r.status, await r.json()

            # r1 dispatches instantly (wait ~0, stays primary); r2 waits
            # out r1's 250 ms service -> queue-wait EWMA blows the 10 ms
            # threshold at ITS OWN grant -> r2 and r3 serve degraded.
            out = await asyncio.gather(one(0.0, 0), one(0.05, 1), one(0.1, 2))
            assert all(status == 200 for status, _ in out), out
            tiers = [body["planner"] for _, body in out]
            assert tiers[0] == "primary"
            assert tiers[1] == "degraded" and tiers[2] == "degraded", tiers
            for _, body in out[1:]:
                # Degraded = served by the shortlist/heuristic planner.
                assert body["origin"] == "heuristic"
                assert body["graph"]["nodes"]
            # Only the primary tier paid the (mock) LLM cost.
            assert planner.calls == 1
            m = await (await client.get("/metrics")).text()
            assert "mcpx_sched_degraded_mode 1.0" in m
            assert 'mcpx_sched_decisions_total{outcome="degraded"} 2.0' in m

        await with_client(build_app(cp), drive)

    asyncio.run(go())


def test_scheduler_disabled_is_passthrough():
    async def go():
        cp, planner = _cp({"enabled": False}, delay_s=0.0)
        await _seed(cp)
        assert cp.scheduler is None  # factory builds no scheduler when off

        async def drive(client):
            r = await client.post("/plan", json={"intent": "plan svc"})
            assert r.status == 200
            body = await r.json()
            # Pass-through response shape: no scheduler field leaks in.
            assert "planner" not in body
            assert set(body) == {"graph", "explanation", "origin", "latency_ms"}
            # And no scheduler series move (gauges exist but stay zero).
            m = await (await client.get("/metrics")).text()
            assert 'mcpx_sched_decisions_total{outcome="admitted"}' not in m

        await with_client(build_app(cp), drive)

    asyncio.run(go())


def test_degraded_plans_never_written_to_cache():
    """A cache hit after recovery must not serve a heuristic plan the
    degraded tier authored."""

    async def go():
        cp, planner = _cp({"enabled": True}, delay_s=0.0)
        await _seed(cp)
        plan, _ = await cp.plan("plan svc cached", degraded=True)
        assert plan.origin == "heuristic"
        assert len(cp._plan_cache) == 0
        # The same intent planned normally afterwards hits the primary.
        plan2, _ = await cp.plan("plan svc cached")
        assert plan2.origin == "llm"
        assert len(cp._plan_cache) == 1

    asyncio.run(go())
