"""Positive fixtures: kernel-route literals at call sites that have an
engine-resolved flag in scope — the suffix-prefill bug class (a class that
resolves self._use_pallas, then pins one dispatch to the jnp fork), plus a
helper that receives the resolved flag as a parameter and drops it."""


def attend(q, *, use_pallas=True, interpret=False):
    return q


class Engine:
    def __init__(self, cfg, head_dim):
        self._use_pallas = cfg.use_pallas and head_dim % 128 == 0

    def decode_segment(self, q):
        # Honors the resolved route: not flagged.
        return attend(q, use_pallas=self._use_pallas)

    def suffix_prefill(self, q):
        return attend(q, use_pallas=False)  # pinned off the resolved route

    def verify_window(self, q):
        return attend(q, interpret=True)  # hardcodes the lowering choice


def forward(q, use_pallas):
    # Receives the resolved flag, then overrides it with a literal.
    return attend(q, use_pallas=False)
