"""Fixture: every call here must trigger async-blocking."""

import subprocess
import time

import requests


async def sleepy():
    time.sleep(1.0)  # line 10: blocking sleep


async def reads_file(path):
    with open(path) as f:  # line 14: sync open
        return f.read()


async def shells_out():
    subprocess.run(["ls"])  # line 19: sync subprocess


async def fetches(url):
    return requests.get(url)  # line 23: sync HTTP


async def pathlib_io(p):
    return p.read_text()  # line 27: blocking filesystem method
