"""Helpers for the cross-module unbounded-retry-loop fixtures."""


def check_time_left(state):
    if state.deadline_at < state.now:
        raise TimeoutError("out of time")


def log_failure(exc):
    print(exc)
