"""unbounded-retry-loop negative across a module boundary: the deadline
consult lives in an innocuously-named imported helper that raises on
expiry — invisible to the old per-function rule, resolved by the call
graph now."""
from .guard import check_time_left


class Client:
    def __init__(self, session, state):
        self.session = session
        self.state = state

    async def fetch(self, url):
        while True:
            try:
                return await self.session.get(url)
            except OSError:
                pass
            check_time_left(self.state)
