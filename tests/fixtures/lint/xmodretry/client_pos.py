"""unbounded-retry-loop positive across a module boundary: the helper the
loop calls merely logs — resolving callees must not blanket-silence the
rule when none of them consults a bound."""
from .guard import log_failure


class Client:
    def __init__(self, session, state):
        self.session = session
        self.state = state

    async def fetch(self, url):
        while True:
            try:
                return await self.session.get(url)
            except OSError as e:
                log_failure(e)
