"""Positive fixtures: host loops that dispatch a jitted step, sync its
result every iteration, and feed the synced value back into the next
dispatch — one device round trip per token."""
import jax
import jax.numpy as jnp


@jax.jit
def step(state, tok):
    return state + 1, jnp.argmax(state) + tok


def decode_while(state, tok, eos):
    out = []
    while tok != eos:
        state, logits = step(state, tok)
        tok = int(jnp.argmax(logits))  # sync fed back into step()
        out.append(tok)
    return out


def decode_for_item(state, tok):
    toks = []
    for _ in range(64):
        state, logits = step(state, tok)
        tok = logits.item()  # sync fed back into step()
        toks.append(tok)
    return toks


def decode_device_get(state, tok):
    # Even the sanctioned batched fetch serializes when it closes the
    # feedback edge: the next dispatch cannot be enqueued until the host
    # has the previous token in hand.
    toks = []
    for _ in range(8):
        state, logits = step(state, tok)
        tok = jax.device_get(logits)  # sync fed back into step()
        toks.append(tok)
    return toks
