"""Fixture: both writes here must trigger async-shared-mutation."""

counts = {"n": 0}


class LazyLoader:
    def __init__(self):
        self._ready = False

    async def ensure(self):
        if self._ready:  # check ...
            return
        await self._load()  # ... yield point: another task re-enters ...
        self._ready = True  # line 14: ... then act — classic lost race

    async def _load(self):
        pass


async def handler():
    counts["n"] += 1
    await do_work()
    counts["n"] -= 1  # line 23: dict counter mutated across the await


async def do_work():
    pass
