"""Positive fixture: cache insertions in request-path async functions
with no eviction or size-bound consult in scope."""


async def handle(self, request):
    key = request["key"]
    self._result_cache[key] = await self.compute(key)  # line 7: flagged
    return self._result_cache[key]


async def track(seen_cache, item):
    seen_cache.append(item)  # line 12: flagged (list cache, no bound)
    return len(item)


async def remember(self, request):
    self._memo.setdefault(request["k"], await self.build(request))  # flagged
