"""sharding-contract negatives: declared axes (through module
constants), agreeing producer/consumer pairs, dynamic specs, and a
donation whose result is rebound rather than aliased."""
import jax
from jax.sharding import Mesh, PartitionSpec as P

DATA_AXIS = "data"

mesh = Mesh((), axis_names=(DATA_AXIS, "model"))


def _enc(x):
    return x


def _dec(x):
    return x


def _axis():
    return "data"


enc = jax.jit(_enc, out_shardings=P(DATA_AXIS))
dec = jax.jit(_dec, in_shardings=(P("data"),))
dyn = jax.jit(_enc, in_shardings=(P(_axis()),))
upd = jax.jit(_enc, donate_argnames=("x",), in_shardings=(P(DATA_AXIS),))


def agreeing(x):
    y = enc(x)
    return dec(y)


def constrained(x):
    return jax.lax.with_sharding_constraint(x, P("model"))


def rebinds(state):
    keep = state
    state = upd(state)
    return state
