"""Positive fixtures for unbounded-retry-loop: retry loops around transport
calls with no deadline or attempt bound."""
import asyncio


async def poll_forever(session):
    while True:
        try:
            return await session.post("http://svc/x", json={})
        except ConnectionError:
            await asyncio.sleep(0.1)


async def hammer(transport, body):
    for _ in range(1000):
        try:
            await transport.post("http://svc/x", body, 5.0)
        except Exception:
            continue


async def aiohttp_idiom(client):
    while True:
        try:
            async with client.get("http://svc/health") as resp:
                if resp.status == 200:
                    return
        except OSError:
            await asyncio.sleep(0.5)


async def outer(session):
    async def inner(client):
        while True:
            try:
                await client.post("http://svc/x", json={})
            except Exception:
                continue

    await inner(session)
