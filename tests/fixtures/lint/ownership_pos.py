"""thread-ownership positives: worker-owned state touched from call paths
not rooted at the worker's entry point (the pre-fix shape of the engine's
aclose-era findings: cross-thread teardown writes, unsanctioned
cross-thread reads, owned-mutator calls from the event loop)."""
import threading

from mcpx.utils.ownership import owned_by


class Tree:
    @owned_by("worker")
    def insert(self, k):
        self.items = k


class Service:
    def __init__(self):
        self.jobs = []  # mcpx: owner[worker]
        self.done_count = 0  # mcpx: owner[worker, atomic]
        self.tree = Tree()

    def start(self):
        threading.Thread(target=self._run, name="svc-worker").start()

    def _run(self):  # mcpx: thread-entry[worker]
        self._step()

    def _step(self):
        self.jobs.append(1)
        self.done_count += 1

    async def handler(self):
        self.jobs = []
        self.jobs.append(2)
        n = len(self.jobs)
        self.tree.insert(3)
        return n + self.done_count
