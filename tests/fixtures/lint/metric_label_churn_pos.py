"""Positive fixture: metrics minted / label values synthesised per request."""
from prometheus_client import Counter, Gauge


async def mint_per_request(registry):
    c = Counter("reqs_total", "requests served", registry=registry)
    c.inc()
    g = Gauge("inflight", "in-flight requests", registry=registry)
    g.set(1)


async def label_churn(metrics, request, intent, url):
    metrics.requests.labels(endpoint=f"/plan/{intent}").inc()
    metrics.requests.labels("intent: " + intent).inc()
    metrics.requests.labels(path=request.path).inc()
    metrics.requests.labels(tenant="tenant-%s" % intent).inc()
    metrics.requests.labels(ep="{}".format(url)).inc()
