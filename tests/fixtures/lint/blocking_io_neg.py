"""Negative fixture: the sanctioned off-loop shapes, read-mode opens, and
writes in code no request path reaches."""
import asyncio
import json
import os


def _persist_sync(payload, path):
    # Blocking write, but only ever dispatched via to_thread below — the
    # executor hop is a spawn edge, never a call edge.
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


async def export_handler(request):
    payload = {"ok": True}
    # Method/function reference handed to the executor: not a call.
    await asyncio.to_thread(_persist_sync, payload, "/tmp/out.json")

    # Nested sync def + to_thread (the FileRegistry pattern).
    def write():
        with open("/tmp/out2.json", "w") as f:
            json.dump(payload, f)

    await asyncio.to_thread(write)
    # Read-mode open: not a write (and string dumps builds, not writes).
    with open("/tmp/in.json") as f:
        data = json.load(f)
    return json.dumps(data)


async def aclose(self):
    # Async, blocking write — but nothing with a `request` param reaches
    # it: shutdown code is not the request path.
    with open("/tmp/snapshot.json", "w") as f:
        json.dump({"state": 1}, f)
