"""Fixture: every handler here must trigger broad-except."""


def swallow():
    try:
        risky()
    except Exception:  # line 7: silent swallow
        pass


def bare():
    try:
        risky()
    except:  # noqa: E722  # line 14: bare except, silent
        return None


def tuple_broad():
    try:
        risky()
    except (ValueError, Exception):  # line 21: Exception hides in a tuple
        return -1


def base_exception():
    try:
        risky()
    except BaseException:  # line 28: even broader, still silent
        return None


def risky():
    raise ValueError("boom")
