"""Fixture: nothing here may trigger async-shared-mutation."""

import asyncio


class Locked:
    def __init__(self):
        self._ready = False
        self._lock = asyncio.Lock()
        self._session = None

    async def ensure(self):
        # Check-then-act under a lock: the await is inside the guard.
        async with self._lock:
            if self._ready:
                return
            await self._load()
            self._ready = True

    async def close(self):
        # Detach-before-await: the write happens before any yield point.
        session, self._session = self._session, None
        if session is not None:
            await session.close()

    def sync_toggle(self):
        # Sync method: no event-loop interleaving to worry about.
        self._ready = not self._ready

    async def _load(self):
        pass
