"""Negative fixture: bounded cache writes (eviction/size consult in
scope), delegated bounded helpers, and non-cache containers."""


async def handle_lru(self, request):
    key = request["key"]
    self._result_cache[key] = await self.compute(key)
    while len(self._result_cache) > 64:
        self._result_cache.popitem(last=False)  # bounded: LRU eviction
    return self._result_cache[key]


async def handle_evict(self, request):
    self._page_cache[request["k"]] = await self.build(request)
    self._evict_pages(16)  # bounded: an eviction helper is consulted


async def handle_del(self, request):
    self._memo[request["k"]] = 1
    if len(self._memo) > 8:
        del self._memo[next(iter(self._memo))]


async def fixed_slot_counters(self, request):
    # Literal keys are fixed slots — a stats dict, not per-request growth.
    self.stats_cache["hits"] += 1
    self.stats_cache["last_status"] = await self.status(request)


async def not_a_cache(self, request):
    results = {}
    results[request["k"]] = await self.compute(request)  # plain dict, silent
    return results


def sync_insert(self, key, value):
    # Sync helper (worker-thread / init-time code): out of scope.
    self._result_cache[key] = value
