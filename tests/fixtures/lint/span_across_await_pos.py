"""Fixture: every delta here spans an await and must trigger
span-across-await-blocking."""

import asyncio
import time


async def wall_clock(session):
    t0 = time.time()
    await session.post("/plan")
    return (time.time() - t0) * 1e3  # line 11: wall-clock delta across await


async def monotonic_clock():
    t0 = time.monotonic()
    await asyncio.sleep(0)
    dt = time.monotonic() - t0  # line 17: monotonic delta across await
    return dt


async def loop_clock(sem, transport):
    t0 = asyncio.get_event_loop().time()
    async with sem:
        response = await transport.post("/x")
    t1 = asyncio.get_event_loop().time()
    latency_ms = (t1 - t0) * 1e3  # line 26: loop-clock delta across async with
    return response, latency_ms
