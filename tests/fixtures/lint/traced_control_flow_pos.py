"""Fixture: both branches here must trigger traced-control-flow."""

import jax
import jax.numpy as jnp


@jax.jit
def branches_on_array(x):
    if jnp.any(x > 0):  # line 9: Python `if` on a traced value
        return x * 2
    return x


@jax.jit
def loops_on_array(x):
    while x.any():  # line 16: Python `while` on a traced reduction
        x = x - 1
    return x
