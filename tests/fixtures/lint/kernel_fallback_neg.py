"""Negative fixtures: kernel-route literals that are CONFIGURATION, not an
override of a resolved flag — reference harnesses, defaults in signatures,
classes without a resolved route — plus call sites that pass the resolved
flag through."""


def attend(q, *, use_pallas=True, interpret=False):  # defaults: not a call site
    return q


class Engine:
    def __init__(self, cfg, head_dim):
        self._use_pallas = cfg.use_pallas and head_dim % 128 == 0
        self._interpret = cfg.interpret

    def decode_segment(self, q):
        return attend(q, use_pallas=self._use_pallas, interpret=self._interpret)

    def suffix_prefill(self, q, route):
        return attend(q, use_pallas=route)  # resolved value as a name


class ReferenceHarness:
    # No resolved flag anywhere in this class: its literals ARE the
    # configuration (a jnp-only correctness reference), not a fork.
    def reference(self, q):
        return attend(q, use_pallas=False)


def forward(q, use_pallas):
    return attend(q, use_pallas=use_pallas)  # passed through


def standalone(q):
    # No resolved flag in scope at all.
    return attend(q, use_pallas=False, interpret=True)
