"""Positives: Python branches on jitted-function parameters that are NOT
declared static — decided at trace time, the hetero-refactor bug class."""

import functools

import jax
import jax.numpy as jnp


def segment(x, temperature, constrained):
    if constrained:  # not static -> trace-time branch
        x = x * 2
    while temperature > 0:  # while on a traced param: same bug
        x = x + 1
        temperature = -1.0
    return x


jit_segment = jax.jit(segment)


@jax.jit
def decorated(x, flag):
    if flag:  # bare @jax.jit: nothing is static
        return x + 1
    return x


@functools.partial(jax.jit, static_argnames=("mode",))
def partial_jit(x, mode, gate):
    if mode:  # static: fine (negative inline)
        x = x * 3
    if gate and mode:  # 'gate' is traced -> positive
        x = jnp.abs(x)
    return x
