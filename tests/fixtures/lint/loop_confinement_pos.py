"""loop-confinement positives: event-loop-owned state reached from
call paths that can originate off the event loop."""
import asyncio
import threading

from mcpx.utils.ownership import owned_by


@owned_by("event_loop")
class Pool:
    def __init__(self):
        self.routed = 0
        self.state = "idle"

    def bump(self):
        self.routed += 1


def thread_body(pool: Pool):
    pool.routed += 1


def start(pool: Pool):
    threading.Thread(target=thread_body, args=(pool,)).start()


async def offload(pool: Pool):
    await asyncio.to_thread(thread_body, pool)


def unspawned_entry(pool: Pool):
    pool.state = "draining"
    pool.bump()


@owned_by("event_loop")
def loop_mutator(pool: Pool):
    pool.routed += 1


def rogue_call(pool: Pool):
    loop_mutator(pool)
