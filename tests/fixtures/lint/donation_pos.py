"""use-after-donation positive: the pool handed to a donating jitted call
is read again before being rebound — a deleted buffer at runtime."""
import jax
import jax.numpy as jnp


def _consume(pool):
    return pool * 2


consume = jax.jit(_consume, donate_argnames=("pool",))


def dispatch():
    pool = jnp.zeros((4,))
    out = consume(pool)
    total = pool.sum()
    return out, total
