"""Negative fixtures for unbounded-retry-loop: bounded retries, give-up
paths, and non-transport awaits must not match."""
import asyncio
import time


async def bounded_by_deadline(session):
    deadline_at = time.monotonic() + 5.0
    while True:
        try:
            return await session.post("http://svc/x", json={})
        except ConnectionError:
            if time.monotonic() > deadline_at:
                raise
            await asyncio.sleep(0.1)


async def gives_up(transport, body):
    for _ in range(5):
        try:
            return await transport.post("http://svc/x", body, 5.0)
        except Exception:
            raise


async def budget_consult(client, budget):
    while True:
        try:
            async with client.get("http://svc/health") as resp:
                return resp.status
        except OSError:
            if not budget.affords(0.1):
                return None
            await asyncio.sleep(0.1)


async def queue_poller_not_transport(q):
    while True:
        try:
            return await q.get()
        except Exception:
            continue


async def no_catch_just_loops(session):
    while True:
        await session.post("http://svc/x", json={})


def sync_never_matches(session):
    while True:
        try:
            return session.post("http://svc/x", json={})
        except ConnectionError:
            continue
