"""Fixture: none of these trigger span-across-await-blocking — the delta
never spans a yield point, the code is sync, or it is deadline arithmetic
(no variable holds a bare clock read that crosses an await)."""

import asyncio
import time


async def delta_after_the_await(work):
    await asyncio.sleep(0)
    t0 = time.monotonic()
    work()
    return time.monotonic() - t0  # same-segment timing: nothing yields inside


def sync_timer(work):
    t0 = time.time()
    work()
    return time.time() - t0  # sync function: not request-path event-loop code


async def deadline_pattern():
    deadline = time.monotonic() + 5.0
    await asyncio.sleep(0)
    return deadline - time.monotonic()  # deadline arithmetic, not an interval


async def clock_reread(work):
    t0 = time.monotonic()
    await asyncio.sleep(0)
    t0 = time.monotonic()  # re-read after the await resets the interval
    work()
    return time.monotonic() - t0
