"""Positive fixture: blocking file writes on the event loop, in a handler
and in a sync helper the handler calls (one and two hops)."""
import json
import os

import numpy as np


def _persist(payload, path):
    # Sync helper, but called DIRECTLY from the async handler below: the
    # write happens on the event loop all the same.
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def _export(rows, path):
    np.save(path, rows)


def _deep(rows, path):
    # Two hops from the handler (handler -> _via -> _deep): still on-loop.
    _export(rows, path)


def _via(rows, path):
    _deep(rows, path)


async def export_handler(request):
    payload = {"ok": True}
    json.dump(payload, open("/tmp/out.json", "w"))
    _persist(payload, "/tmp/out2.json")
    _via([1, 2, 3], "/tmp/out3.npy")
    return payload
