"""thread-ownership negatives: worker-only mutation paths, GIL-atomic
cross-thread reads, construction writes, and unowned boundary state."""
import threading

from mcpx.utils.ownership import owned_by


class Tree:
    @owned_by("worker")
    def insert(self, k):
        self.items = k


class Service:
    def __init__(self):
        self.jobs = []  # mcpx: owner[worker]
        self.done_count = 0  # mcpx: owner[worker, atomic]
        self.tree = Tree()
        self.inbox = []

    def start(self):
        threading.Thread(target=self._run, name="svc-worker").start()

    def _run(self):  # mcpx: thread-entry[worker]
        self._step()

    def _step(self):
        self.jobs.append(1)
        self.tree.insert(2)
        self.done_count += 1

    async def handler(self):
        self.inbox.append("job")  # unowned queue boundary: fine
        return self.done_count  # atomic read: sanctioned
