"""Negative fixture: init-time construction and bounded label sources."""
import collections

from prometheus_client import Counter


class Metrics:
    def __init__(self, registry):
        # Construction at init time, once per registry: the sanctioned home.
        self.requests = Counter(
            "reqs_total", "requests", ["endpoint", "status"], registry=registry
        )


async def bounded_labels(metrics, endpoint, status, degraded):
    # Plain names bound upstream (route template, outcome enum) and
    # literals: bounded by construction.
    metrics.requests.labels(endpoint=endpoint, status=status).inc()
    metrics.requests.labels(endpoint="/plan", status="ok").inc()
    metrics.requests.labels(status="degraded" if degraded else "admitted").inc()


async def not_prometheus(items):
    # collections.Counter is not a metric; a two-string call shape is what
    # distinguishes the prometheus constructors.
    c = collections.Counter()
    c["x"] += 1
    tally = collections.Counter(items)
    return tally
