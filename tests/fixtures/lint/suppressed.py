"""Fixture: one real suppression (consumed) and one dead one (reported)."""

import time


async def tolerated():
    time.sleep(0.01)  # mcpx: ignore[async-blocking] - fixture: justified one-off


async def clean():
    return 42  # mcpx: ignore[async-blocking] - nothing to suppress: dead annotation
