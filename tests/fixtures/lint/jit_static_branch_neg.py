"""Negatives: static-arg branches, presence checks, shadowed names and
un-jitted helpers must not trip jit-static-branch."""

import functools

import jax
import jax.numpy as jnp


def segment(x, mask, *, iters, chunk):
    if chunk > 1 and iters > 0:  # both declared static below
        x = x * 2
    if mask is not None:  # presence check: static at trace time
        x = jnp.where(mask, x, 0.0)
    if x.ndim == 2 and x.shape[0] > 1:  # shape metadata: static too
        x = x[:1]

    def inner(chunk):  # shadows the outer param: its own local
        if chunk:
            return 1
        return 0

    return x + inner(0)


jit_segment = jax.jit(segment, static_argnames=("iters", "chunk"))


@functools.partial(jax.jit, static_argnames=("mode",))
def partial_jit(x, mode):
    if mode:
        x = x * 3
    return x


def plain_helper(x, flag):  # never jitted: Python branching is fine
    if flag:
        return x + 1
    return x
