"""Call-graph golden fixture: direct calls, method calls, an imported
helper, a thread spawn and a task spawn."""
import asyncio
import threading

from .util import helper


class Runner:
    def __init__(self):
        self.count = 0

    def start(self):
        threading.Thread(target=self._loop, name="runner").start()

    def _loop(self):
        self.tick()

    def tick(self):
        helper()

    async def serve(self):
        asyncio.create_task(self.handle())

    async def handle(self):
        self.tick()
