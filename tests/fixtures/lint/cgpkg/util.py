"""Leaf helpers for the call-graph golden fixture."""


def helper():
    return 1


def unused():
    return 2
