"""Negative fixtures: evict-without-refcount-consult stays silent."""


class Node:
    def __init__(self):
        self.refs = 0
        self.pages = []


class DirectConsult:
    def __init__(self):
        self.nodes = {}

    def pin(self, key):
        self.nodes[key].refs += 1

    def evict(self, need):
        # consults the refcount inline before any removal
        for key in list(self.nodes):
            victim = self.nodes[key]
            if victim.refs != 0:
                continue
            self.nodes.pop(key)
            need -= 1


class HelperConsult:
    def __init__(self):
        self.nodes = {}

    def pin(self, key):
        self.nodes[key].refs += 1

    def _evictable(self, node):
        return node.refs == 0 and not node.pages

    def evict_lru(self):
        for key in list(self.nodes):
            if self._evictable(self.nodes[key]):
                self.nodes.pop(key)


class PlainLru:
    """No refcounts anywhere: a plain LRU may evict freely (bounding it is
    unbounded-cache-growth's business, not this rule's)."""

    def __init__(self):
        self.entries = {}

    def evict(self):
        while len(self.entries) > 8:
            self.entries.pop(next(iter(self.entries)))
