"""blocking-transfer negatives: off-loop readbacks, to_thread'd
closures, and host-native values on the loop stay silent."""
import asyncio

import jax
import numpy as np


def _step(x):
    return x


jstep = jax.jit(_step)


def offline_report(engine):
    st = engine.queue_stats()
    return float(st["depth"])


async def handler(request, engine):
    def _read():
        return float(engine.queue_stats()["depth"])

    depth = await asyncio.to_thread(_read)
    n = float(len(request.tools))
    return depth, n


async def background(engine):
    return np.asarray(jstep(1))
