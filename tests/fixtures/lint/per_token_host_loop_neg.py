"""Negative fixtures: device-chained loops, non-feedback syncs, and
non-jitted feedback must not match per-token-host-loop."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step(state, tok):
    return state + 1, jnp.argmax(state) + tok


def python_step(state, tok):
    return state, tok + 1


def decode_device_chained(state, tok):
    # The good pattern: the token stays a device value across iterations;
    # ONE batched fetch after the loop.
    toks = []
    for _ in range(64):
        state, tok = step(state, tok)
        toks.append(tok)
    return jax.device_get(toks)


def train_metrics_only(state, batch):
    # Per-iteration sync that is NOT fed back into the dispatch: the
    # jit-host-sync hot-loop rule's business, not this rule's.
    losses = []
    for _ in range(10):
        state, loss = step(state, batch)
        losses.append(float(loss))
    return losses


def feedback_through_python_fn(state, tok):
    # Feedback into a plain-Python helper, no jitted dispatch in the loop
    # consuming the synced value.
    out = []
    while tok < 10:
        arr = np.asarray([tok])
        state, tok = python_step(state, int(arr[0]))
        out.append(tok)
    return out
