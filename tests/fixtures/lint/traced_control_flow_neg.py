"""Fixture: nothing here may trigger traced-control-flow."""

import jax
import jax.numpy as jnp


@jax.jit
def static_branches(x, chunk: int, constrained: bool):
    # Static-argument control flow is resolved at trace time — fine.
    if chunk > 1 and constrained:
        x = x * chunk
    while chunk > 4:
        chunk -= 1
    return jnp.where(x > 0, x, 0)  # device-side select, not Python flow


def host_code(rows):
    # Outside any traced scope, branching on array reductions is ordinary
    # (eager) numpy-style code.
    if jnp.any(jnp.asarray(rows) > 0):
        return True
    return False
