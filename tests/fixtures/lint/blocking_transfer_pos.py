"""blocking-transfer positives: synchronizing device readbacks inside
loop-side code — handler-direct, comprehension taint, and a sync
helper one hop below an async request handler."""
import jax
import numpy as np


def _step(x):
    return x


jstep = jax.jit(_step)


async def handler(request, engine):
    depth = float(engine.queue_stats()["depth"])
    arr = jstep(request.payload)
    host = np.asarray(arr)
    vals = {k: float(v) for k, v in engine.queue_stats().items()}
    return depth, host, vals


def probe(engine):
    st = engine.queue_stats()
    return int(st["active"])


async def poll(request, engine):
    return probe(engine)
