"""Clean twin of handler_pos: the request value is quantized onto a fixed
bucket grid before it can reach the static arg — finitely many
executables by construction, the engine's sanctioned `_bucket` idiom."""
from .engine_mod import run_decode, size_bucket


class PlanRequest:  # mcpx: request-payload
    max_tokens: int


async def handle(req: PlanRequest):
    n = size_bucket(req.max_tokens)
    return await run_decode(n)
