"""A request field crosses a module boundary into a static arg: the PR 7
retrace-storm shape (one compile per distinct max_tokens) that the
per-function jit-static-branch rule cannot see."""
from .engine_mod import run_decode


class PlanRequest:  # mcpx: request-payload
    max_tokens: int


async def handle(req: PlanRequest):
    n = req.max_tokens
    return await run_decode(n)
