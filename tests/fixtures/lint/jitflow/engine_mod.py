"""Mini jitted engine for the jit-contract fixtures: `step` bakes `width`
into the executable (static arg), so whoever calls `run_decode` decides
how many executables exist. Scanned ALONE this file is clean — the taint
arrives only through a caller in another module."""
import jax
import jax.numpy as jnp


def _step_impl(x, width):
    return x[:width] + 1


step = jax.jit(_step_impl, static_argnames=("width",))


async def run_decode(width):
    x = jnp.zeros((8,))
    return step(x, width)


def size_bucket(n):
    return 8 if n <= 8 else 64
