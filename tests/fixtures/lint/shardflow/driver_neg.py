"""Agreeing stage pair: producer out-sharding matches the consumer's
declared in-sharding, so the chain is reshard-free and silent."""
from .stages import encode, rank


def drive(tokens):
    feats = encode(tokens)
    return rank(feats)
