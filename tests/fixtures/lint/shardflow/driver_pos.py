"""Chains the mismatched stages: the boundary buffer is resharded
(an all-to-all) on every call."""
from .stages import decode, encode


def drive(tokens):
    feats = encode(tokens)
    out = decode(feats)
    return out
