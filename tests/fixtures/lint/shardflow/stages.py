"""Two jitted stages whose declared shardings disagree on the boundary
buffer (the all-to-all-per-step shape), plus an agreeing consumer."""
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh((), ("data", "model"))


def _encode(tokens):
    return tokens


def _decode(feats):
    return feats


encode = jax.jit(_encode, out_shardings=P("data"))
decode = jax.jit(_decode, in_shardings=(P("model"),))
rank = jax.jit(_decode, in_shardings=(P("data"),))
