"""Positive fixtures: evict-without-refcount-consult."""


class Node:
    def __init__(self):
        self.refs = 0  # the class IS refcount-aware: pins exist
        self.pages = []


class TieredCache:
    def __init__(self):
        self.nodes = {}
        self.allocator = object()

    def pin(self, key):
        self.nodes[key].refs += 1

    def evict(self, need):
        # removes entries with no refs consult anywhere in scope: a pinned
        # node's pages go back to the allocator under a live reader
        for key in list(self.nodes):
            victim = self.nodes.pop(key)
            self.allocator.free(victim.pages)
            if need <= 0:
                break
            need -= 1


class HostTier:
    def __init__(self):
        self.runs = {}

    def adopt(self, node, run):
        node.refs = 0
        self.runs[node] = run

    def reclaim_lru(self, n):
        while n and self.runs:
            node, _run = next(iter(self.runs.items()))
            del self.runs[node]
            n -= 1
