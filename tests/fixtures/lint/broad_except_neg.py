"""Fixture: nothing here may trigger broad-except."""

import logging
import traceback

log = logging.getLogger(__name__)


def narrow():
    try:
        risky()
    except ValueError:  # specific type: fine even when silent
        return None


def logs_it():
    try:
        risky()
    except Exception:
        log.exception("risky failed; continuing")


def logs_via_get_logger():
    try:
        risky()
    except Exception as e:
        logging.getLogger("fixture").warning("risky failed: %s", e)


def reraises():
    try:
        risky()
    except Exception:
        raise


def prints_traceback():
    try:
        risky()
    except Exception:
        traceback.print_exc()
        return None


def risky():
    raise ValueError("boom")
