"""unbounded-cache-growth positive across a helper boundary: the helper
the container is handed to never consults a bound either — routing
through a function must not blanket-silence the rule."""
from .store import put_unbounded


class Plans:
    def __init__(self):
        self._plan_cache = {}

    async def lookup(self, key, value):
        put_unbounded(self._plan_cache, key, value)
        self._plan_cache[key] = value
