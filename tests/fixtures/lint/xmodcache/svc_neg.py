"""unbounded-cache-growth negatives across helper boundaries: the bound
consult lives in an imported helper (passed the container) or a same-class
trim method — the false-positive class the dataflow migration killed."""
from .store import put_bounded


class Plans:
    def __init__(self):
        self._plan_cache = {}

    def _trim(self):
        while len(self._plan_cache) > 64:
            self._plan_cache.popitem()

    async def lookup(self, key, value):
        put_bounded(self._plan_cache, key, value)
        self._plan_cache[key] = value

    async def lookup_via_method(self, key, value):
        self._trim()
        self._plan_cache[key] = value
