"""Cache helpers for the cross-module unbounded-cache-growth fixtures."""


def put_bounded(cache, key, value):
    if len(cache) > 64:
        cache.popitem()
    cache[key] = value


def put_unbounded(cache, key, value):
    cache[key] = value
