"""use-after-donation negatives: rebinding from the dispatch outputs
closes the window, and a sibling `else` arm is not after the dispatch
(the engine's `_ensure_prefix` shape that once false-positived)."""
import jax
import jax.numpy as jnp


def _consume(pool):
    return pool * 2


consume = jax.jit(_consume, donate_argnames=("pool",))


def dispatch_rebound():
    pool = jnp.zeros((4,))
    pool = consume(pool)
    return pool.sum()


def dispatch_branchy(flag):
    pool = jnp.zeros((4,))
    if flag:
        out = consume(pool)
    else:
        out = pool.sum()
    pool = jnp.zeros((4,))
    return out, pool
