"""loop-confinement negatives: coroutine writers, loop-spawned
callbacks, ctor writes, marked mutators and cross-thread READS."""
import asyncio
import threading

from mcpx.utils.ownership import owned_by


@owned_by("event_loop")
class Board:
    def __init__(self):
        self.depth = 0
        self.seen = {}


async def refresh(board: Board):
    board.depth += 1


def helper(board: Board):
    board.seen["k"] = 1


async def tick(board: Board):
    helper(board)


def on_loop(board: Board):
    board.depth -= 1


async def schedule(board: Board):
    loop = asyncio.get_running_loop()
    loop.call_soon(on_loop, board)


@owned_by("event_loop")
def marked_mutator(board: Board):
    board.depth = 0


def reader_thread(board: Board):
    return board.depth


def spawn_reader(board: Board):
    threading.Thread(target=reader_thread, args=(board,)).start()
