"""Fixture: monotonic deltas, lone wall-clock timestamps, cross-host
timestamp comparisons, and sync offline code — none may trigger
wall-clock-duration."""

import time


async def monotonic_delta(request):
    t0 = time.monotonic()
    await request.app.plan(request)
    return (time.monotonic() - t0) * 1e3  # monotonic: the correct clock


async def timestamp_only(sink):
    await sink.put({"at": time.time()})  # a timestamp, never differenced
    return time.time()


async def cross_host_ttl(obj):
    # One wall-clock operand against a REMOTE timestamp: no monotonic
    # alternative exists across hosts (the telemetry-mirror TTL idiom).
    return time.time() - float(obj.get("at", 0))


def offline_report():
    # Sync code is outside the request path (CLI training harness idiom).
    t0 = time.time()
    total = sum(range(1000))
    return total, time.time() - t0
