"""Fixture: two consecutive blank lines are fine; nothing triggers."""

A = 1


B = 2


def f():
    return A + B
