"""Fixture: the gap below must trigger blank-lines."""

A = 1



B = 2
