"""Fixture: every marked line must trigger jit-host-sync."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@jax.jit
def decorated(x):
    y = np.asarray(x)  # line 13: host transfer inside jit
    return float(x.sum()) + y.item()  # line 14: float() and .item()


@functools.partial(jax.jit, static_argnames=("n",))
def partial_decorated(x, n):
    jax.device_get(x)  # line 19: device_get inside jit
    return x * n


def _scan_body(carry, x):
    v = int(x)  # line 24: int() on traced scan input
    return carry + v, x


def uses_scan(xs):
    return lax.scan(_scan_body, 0, xs)


step = jax.jit(lambda p, b: p)


def hot_loop(params, batches):
    for b in batches:
        params = step(params, b)
        loss = step(params, b)
        print(float(loss))  # line 39: per-iteration sync on jitted result
    return params
