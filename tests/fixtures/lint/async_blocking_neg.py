"""Fixture: nothing here may trigger async-blocking."""

import asyncio
import time


def sync_helper(path):
    time.sleep(0.1)  # sync function: its caller decides the regime
    with open(path) as f:
        return f.read()


async def polite(path):
    await asyncio.sleep(0.1)
    return await asyncio.to_thread(sync_helper, path)


async def offloaded(path):
    # A nested *sync* def is a different execution regime (to_thread target):
    # its body must not be charged to the enclosing coroutine.
    def read():
        with open(path) as f:
            return f.read()

    return await asyncio.to_thread(read)
