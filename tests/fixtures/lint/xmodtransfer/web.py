"""Async handler two call hops above a readback of a cross-module
device-sourced value."""
from .devstats import device_stats


def summarize(engine):
    st = device_stats(engine)
    return float(st["depth"])


def render(engine):
    return summarize(engine)


async def handler(request, engine):
    return render(engine)
