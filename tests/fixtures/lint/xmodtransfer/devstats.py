"""Device-adjacent helper: forwards the raw device-backed mapping."""


def device_stats(engine):
    return engine.queue_stats()
