"""Fixture: nothing here may trigger jit-host-sync."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@jax.jit
def clean(x):
    y = jnp.asarray(x)  # jnp stays on device
    return jnp.sum(y * 2.0)


def _scan_body(carry, x):
    return carry + x, jnp.where(x > 0, x, 0)


def uses_scan(xs):
    return lax.scan(_scan_body, jnp.asarray(0.0), xs)


def host_prep(rows):
    # np conversions OUTSIDE any traced scope are ordinary host work.
    arr = np.asarray(rows)
    return int(arr.sum())


step = jax.jit(lambda p, b: p)


def batched_fetch_loop(params, batches):
    outs = []
    for b in batches:
        params = step(params, b)
        outs.append(params)  # keep handles; no per-step conversion
    # ONE sync after the loop is the sanctioned pattern.
    return [float(jnp.sum(o)) for o in outs]
