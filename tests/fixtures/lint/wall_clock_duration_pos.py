"""Fixture: every subtraction here differences a wall-clock pair into a
duration in async request-path code and must trigger wall-clock-duration."""

import datetime
import time


async def handler(request):
    t0 = time.time()
    result = await request.app.plan(request)
    latency_ms = (time.time() - t0) * 1e3  # line 11: call minus tracked name
    return result, latency_ms


async def window(events):
    start = datetime.datetime.now()
    await events.drain()
    return datetime.datetime.now() - start  # line 18: datetime pair


async def pair(queue):
    t0 = time.time()
    item = await queue.get()
    t1 = time.time()
    wait_s = t1 - t0  # line 25: two tracked wall-clock names
    return item, wait_s
