"""sharding-contract positives: an undeclared mesh axis, a
producer/consumer sharding disagreement, and a live alias of a
donated sharded buffer."""
import jax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh((), ("data", "model"))


def _enc(x):
    return x


def _dec(x):
    return x


def _upd(state):
    return state


enc = jax.jit(_enc, out_shardings=P("data"))
dec = jax.jit(_dec, in_shardings=(P("model"),))
bad = jax.jit(_enc, in_shardings=(P("tensor"),))
upd = jax.jit(_upd, donate_argnames=("state",), in_shardings=(P("data"),))


def chain(x):
    y = enc(x)
    z = dec(y)
    return z


def run(state):
    keep = state
    out = upd(state)
    return keep, out
