"""Grammar-aware speculative decoding (ISSUE 6): drafter + one-forward
verification in the heterogeneous slab. The invariants pinned here:

  - off = byte-identical pass-through (the repo's config-gated-subsystem
    convention) and the spec executable is never even dispatched;
  - on  = greedy outputs byte-identical to off (the sequential-sample
    accept rule is exact) while doing strictly fewer model forwards;
  - constrained rows can NEVER emit a DFA-inadmissible token under
    speculation, whatever the grammar or temperature (property test over
    seeded grammars);
  - one compile serves every resident-grammar × accept-pattern mix;
  - stacked-DFA slot recycling survives rows retiring with different
    accepted lengths.
"""

import asyncio

from tests.helpers import release_prefix_cache

from mcpx.core.config import MCPXConfig
from mcpx.engine.engine import InferenceEngine
from mcpx.planner.grammar import build_plan_grammar


def make_engine(**engine_overrides):
    cfg = MCPXConfig.from_dict(
        {
            "model": {"size": "test", "max_seq_len": 256},
            "engine": {
                "use_pallas": False,  # jnp reference attention on CPU
                "max_batch_size": 4,
                "max_decode_len": 96,
                "kv_page_size": 16,
                "max_pages_per_seq": 16,
                "temperature": 0.0,
                **engine_overrides,
            },
        }
    )
    return InferenceEngine(cfg)


def spec_engine(**spec):
    return make_engine(
        hetero_batch=True, speculative={"enabled": True, "k": 4, **spec}
    )


def _spec_counters(eng):
    drafted = sum(
        eng.metrics.spec_drafted.labels(cls=c)._value.get()
        for c in ("constrained", "free")
    )
    accepted = sum(
        eng.metrics.spec_accepted.labels(cls=c)._value.get()
        for c in ("constrained", "free")
    )
    return drafted, accepted


def test_spec_off_is_passthrough_parity():
    """speculative.enabled=false is a byte-identical pass-through of the
    legacy hetero decode: same outputs as an engine that never heard of
    the subsystem, zero drafted tokens, spec executable never dispatched."""

    async def go():
        eng_legacy = make_engine(hetero_batch=True)
        eng_off = make_engine(
            hetero_batch=True, speculative={"enabled": False, "k": 4}
        )
        await eng_legacy.start()
        await eng_off.start()
        try:
            tok = eng_legacy.tokenizer
            for text, budget in [
                ("plan: compose the services. JSON:", 48),
                ("q", 24),
            ]:
                a = await eng_legacy.generate(tok.encode(text), max_new_tokens=budget)
                b = await eng_off.generate(tok.encode(text), max_new_tokens=budget)
                assert a.text == b.text, (text, a.text, b.text)
            free_a = await eng_legacy.generate(
                tok.encode("free"), max_new_tokens=8, constrained=False
            )
            free_b = await eng_off.generate(
                tok.encode("free"), max_new_tokens=8, constrained=False
            )
            assert free_a.token_ids == free_b.token_ids
            assert _spec_counters(eng_off) == (0.0, 0.0)
            qs = eng_off.queue_stats()
            assert qs["spec_accept_rate"] == 0.0
        finally:
            await eng_legacy.aclose()
            await eng_off.aclose()

    asyncio.run(go())


def test_spec_on_greedy_matches_spec_off():
    """The accept rule is exact: greedy outputs are byte-identical with
    speculation on vs off — across budgets, prompts and a registry-trie
    grammar — while the spec engine drafts, accepts, and does strictly
    fewer model forwards than tokens emitted."""

    async def go():
        eng_off = make_engine(hetero_batch=True)
        eng_on = spec_engine()
        await eng_off.start()
        await eng_on.start()
        try:
            tok = eng_off.tokenizer
            names = ["svc-alpha", "svc-beta", "rank-gamma"]
            g_off = build_plan_grammar(eng_off.tokenizer, names)
            g_on = build_plan_grammar(eng_on.tokenizer, names)
            prompts = ["plan: compose the services. JSON:", "q"]
            budgets = [eng_off.grammar.min_len, 24, 96]
            for text in prompts:
                for budget in budgets:
                    a = await eng_off.generate(
                        tok.encode(text), max_new_tokens=budget
                    )
                    b = await eng_on.generate(
                        tok.encode(text), max_new_tokens=budget
                    )
                    assert a.text == b.text, (text, budget, a.text, b.text)
            a = await eng_off.generate(
                tok.encode("trie plan. JSON:"), max_new_tokens=48, grammar=g_off
            )
            b = await eng_on.generate(
                tok.encode("trie plan. JSON:"), max_new_tokens=48, grammar=g_on
            )
            assert a.text == b.text
            # Free-form greedy rows: the drafter proposes unmasked, and the
            # full-window verification argmax must reproduce the legacy
            # last-position path token for token.
            fa = await eng_off.generate(
                tok.encode("free greedy"), max_new_tokens=12, constrained=False
            )
            fb = await eng_on.generate(
                tok.encode("free greedy"), max_new_tokens=12, constrained=False
            )
            assert fa.token_ids == fb.token_ids
            drafted, accepted = _spec_counters(eng_on)
            assert drafted > 0 and accepted > 0
            fwd = eng_on.metrics.decode_forwards._value.get()
            toks = eng_on.metrics.decode_tokens._value.get()
            assert fwd < toks, (
                f"speculation did not amortise: {fwd} forwards / {toks} tokens"
            )
            qs = eng_on.queue_stats()
            assert 0.0 < qs["spec_accept_rate_constrained"] <= 1.0
        finally:
            await eng_off.aclose()
            await eng_on.aclose()

    asyncio.run(go())


def test_spec_grammar_draft_mode_exact():
    """draft='grammar' (forced-successor drafting only, zero drafter
    compute) is equally exact under greedy decode and still amortises on
    plan JSON (single-successor chains draft themselves)."""

    async def go():
        eng_off = make_engine(hetero_batch=True)
        eng_on = spec_engine(draft="grammar")
        await eng_off.start()
        await eng_on.start()
        try:
            tok = eng_off.tokenizer
            p = tok.encode("plan: compose. JSON:")
            a = await eng_off.generate(p, max_new_tokens=48)
            b = await eng_on.generate(p, max_new_tokens=48)
            assert a.text == b.text
            drafted, accepted = _spec_counters(eng_on)
            # Forced drafts verify with certainty: everything drafted in
            # grammar mode must have been accepted.
            assert drafted > 0
            assert accepted == drafted
            assert (
                eng_on.metrics.decode_forwards._value.get()
                < eng_on.metrics.decode_tokens._value.get()
            )
        finally:
            await eng_off.aclose()
            await eng_on.aclose()

    asyncio.run(go())


def test_spec_constrained_rows_never_emit_inadmissible():
    """Property over seeded grammars: whatever the registry trie and
    whatever the temperature, a constrained row under speculation only
    ever emits legal DFA prefixes — accepted drafts are admissible by
    construction and the correction is sampled under the same mask."""
    import random

    async def go():
        eng = spec_engine()
        await eng.start()
        try:
            tok = eng.tokenizer
            for seed in range(4):
                rng = random.Random(seed)
                names = [
                    f"{rng.choice(['data', 'rank', 'sum'])}-"
                    f"{rng.choice(['etl', 'ml', 'api'])}-{rng.randrange(100):02d}"
                    for _ in range(rng.randrange(2, 6))
                ]
                g = build_plan_grammar(tok, sorted(set(names)))
                results = await asyncio.gather(
                    *(
                        eng.generate(
                            tok.encode(f"seeded plan {seed}-{i}. JSON:"),
                            max_new_tokens=rng.choice([g.min_len, 24, 48]),
                            temperature=t,
                            grammar=g,
                        )
                        for i, t in enumerate([0.0, 0.9, 0.0, 1.3])
                    )
                )
                for r in results:
                    state = g.walk(r.text)
                    assert state != g.dead_state, (seed, r.text)
            drafted, _ = _spec_counters(eng)
            assert drafted > 0
            release_prefix_cache(eng)
            assert eng._allocator.stats().sequences == 0
            eng._allocator.check_invariants()
        finally:
            await eng.aclose()

    asyncio.run(go())


def test_spec_segment_compiles_once_across_grammar_mix():
    """Executable-count acceptance: the fixed [rows, K+1] window means ONE
    spec-segment compile serves every resident-grammar combination, accept
    pattern, temperature and constrained/free mix."""
    from tests.helpers import count_compiles

    async def go(compiles):
        eng = spec_engine()
        await eng.start()
        try:
            p = eng.tokenizer.encode("plan: compose. JSON:")
            await eng.generate(p, max_new_tokens=24)
            n0 = len(compiles)
            assert n0 >= 1, "first spec segment never compiled?"
            g1 = build_plan_grammar(eng.tokenizer, ["svc-a", "svc-b"])
            g2 = build_plan_grammar(eng.tokenizer, ["other-x", "other-y"])
            await asyncio.gather(
                eng.generate(p, max_new_tokens=24, grammar=g1),
                eng.generate(p, max_new_tokens=24, grammar=g2, temperature=0.7),
                eng.generate(
                    eng.tokenizer.encode("free"), max_new_tokens=8, constrained=False
                ),
            )
            assert len(compiles) == n0, (
                f"spec segment recompiled for new grammars/configs/accept "
                f"patterns: {len(compiles) - n0} extra compiles"
            )
        finally:
            await eng.aclose()

    with count_compiles("_hetero_segment_spec_impl") as compiles:
        asyncio.run(go(compiles))


def test_spec_slot_recycle_with_mixed_accepted_lengths():
    """Slot recycling under speculation: rows retiring with DIFFERENT
    accepted lengths (two grammars through 2 slots, a free row, a hot row)
    release their stacked-DFA slots and pages cleanly, and the overflow
    grammar still defers-then-completes."""

    async def go():
        eng = make_engine(
            hetero_batch=True,
            hetero_grammar_slots=2,
            speculative={"enabled": True, "k": 4},
        )
        await eng.start()
        try:
            tok = eng.tokenizer
            p = tok.encode("plan: q. JSON:")
            g1 = build_plan_grammar(tok, ["aaa-svc"])
            g2 = build_plan_grammar(tok, ["bbb-svc-with-a-much-longer-name"])
            r1, r2, r3, r4 = await asyncio.gather(
                eng.generate(p, max_new_tokens=32, grammar=g1),
                eng.generate(p, max_new_tokens=64, grammar=g2),
                eng.generate(tok.encode("free"), max_new_tokens=8, constrained=False),
                eng.generate(p, max_new_tokens=24, temperature=0.9),
            )
            assert '"s":"aaa-svc"' in r1.text
            assert '"s":"bbb-svc-with-a-much-longer-name"' in r2.text
            assert eng.grammar.walk(r4.text) != eng.grammar.dead_state
            assert eng.queue_stats()["resident_grammars"] == 0
            assert all(n == 0 for n in eng._dfa_slot_refs)
            release_prefix_cache(eng)
            assert eng._allocator.stats().sequences == 0
            eng._allocator.check_invariants()
        finally:
            await eng.aclose()

    asyncio.run(go())


def test_spec_live_flip_off_keeps_latched_geometry():
    """A live `speculative.enabled` flip-off while spec-admitted rows are
    resident must not retrace: dispatch reads the slab's LATCHED spec_k /
    spec_draft, never the live config (an unwarmed K=0 executable compiled
    mid-serving is exactly the stall the latch contract forbids). Requests
    before, during, and after the flip all complete correctly, and no new
    spec-segment compile ever happens."""
    from tests.helpers import count_compiles

    async def go(compiles):
        eng = spec_engine()
        await eng.start()
        try:
            tok = eng.tokenizer
            p = tok.encode("plan: compose. JSON:")
            await eng.generate(p, max_new_tokens=24)  # prime the executable
            n0 = len(compiles)

            async def flip_then_request():
                await asyncio.sleep(0.05)  # land while rows are resident
                eng.config.engine.speculative.enabled = False
                return await eng.generate(p, max_new_tokens=24)

            r1, r2 = await asyncio.gather(
                eng.generate(p, max_new_tokens=96), flip_then_request()
            )
            r3 = await eng.generate(p, max_new_tokens=24)
            for r in (r1, r2, r3):
                assert eng.grammar.walk(r.text) != eng.grammar.dead_state
            assert len(compiles) == n0, (
                f"live flip-off retraced the spec segment "
                f"({len(compiles) - n0} extra compiles)"
            )
            assert eng._slab.n_active == 0
        finally:
            await eng.aclose()

    with count_compiles("_hetero_segment_spec_impl") as compiles:
        asyncio.run(go(compiles))


def test_stacked_window_admissibility_matches_draft_walk_masks():
    """Property over seeded grammars: the verify-window masks the drafter's
    DFA walk emits (``draft_window``, gathered at the states it visits)
    equal the spelled-out reference ``stacked_window_admissibility`` at
    every position verification can consume — position 0, the unbroken
    proposal prefix, and the correction slot — across start states,
    mid-plan trie interiors, a free row, both draft modes, and a budget
    horizon tight enough that the finishability mask binds (the
    degrade-to-legal path)."""
    import random

    import jax.numpy as jnp
    import numpy as np

    from mcpx.engine.speculative import draft_window
    from mcpx.models.tokenizer import ByteTokenizer
    from mcpx.planner.grammar import (
        build_trivial_grammar,
        stacked_spec_tables,
        stacked_tables,
        stacked_window_admissibility,
    )

    tok = ByteTokenizer()
    K = 4
    rng = random.Random(7)
    nprng = np.random.default_rng(7)
    names1 = sorted({f"svc-{rng.randrange(100):02d}" for _ in range(3)})
    names2 = sorted({f"rank-{rng.choice(['etl', 'ml'])}" for _ in range(2)})
    g1 = build_plan_grammar(tok, names1)
    g2 = build_plan_grammar(tok, names2)
    slots = [build_trivial_grammar(tok), g1, g2]
    strans, smask, sdist, sactive, seos = stacked_tables(slots, 512)
    sdist_succ, _inv = stacked_spec_tables(slots, 512)
    sdfa = tuple(
        jnp.asarray(t) for t in (strans, smask, sdist_succ, sactive, seos)
    )
    ref_tables = tuple(
        jnp.asarray(t) for t in (strans, smask, sdist, sactive, seos)
    )

    rows = []  # (grammar slot, DFA state, emitted, constrained)
    for gi, g, name in ((1, g1, names1[0]), (2, g2, names2[0])):
        plan = '{"steps":[{"s":"%s","in":[],"next":[]}]}' % name
        for cut in (0, 1, 8, 12, 14, len(plan) - 4):
            st = g.walk(plan[:cut])
            assert st != g.dead_state
            rows.append((gi, st, cut, True))
    rows.append((0, slots[0].start_state, 5, False))  # free row
    B = len(rows)
    dfa_id = jnp.asarray([r[0] for r in rows], jnp.int32)
    st = jnp.asarray([r[1] for r in rows], jnp.int32)
    emitted = jnp.asarray([r[2] for r in rows], jnp.int32)
    cons_v = jnp.asarray([r[3] for r in rows])
    done = jnp.zeros((B,), bool)
    H = 16
    embed = jnp.asarray(
        nprng.normal(size=(tok.vocab_size, H)), jnp.float32
    )
    cur = jnp.full((B,), tok.encode("{")[0], jnp.int32)
    hstate = jnp.zeros((B, H), jnp.float32)
    free_mask = (
        jnp.ones((tok.vocab_size,), bool)
        .at[tok.eos_id]
        .set(False)
        .at[tok.pad_id]
        .set(False)
    )

    for slack, mode in [(48, "recurrent"), (6, "recurrent"), (48, "grammar")]:
        budgets = emitted + slack
        _p_toks, p_use, s_before, s_fin, masks = draft_window(
            embed,
            sdfa,
            dfa_id,
            st,
            cur,
            hstate,
            emitted,
            budgets,
            done,
            cons_v,
            free_mask,
            tok.pad_id,
            k=K,
            mode=mode,
        )
        states = jnp.concatenate([s_before, s_fin[:, None]], axis=1)
        rem = (
            budgets[:, None]
            - (emitted[:, None] + jnp.arange(K + 1)[None, :])
            - 1
        )
        ref = stacked_window_admissibility(ref_tables, dfa_id, states, rem)
        # Positions verification can consume: j=0 always (its mask was
        # gathered before any proposal could stop), j>0 while every prior
        # step proposed (a stopped row's later slots repeat its frozen
        # state/budget — out of the comparison by the stop bound).
        prefix_ok = jnp.cumprod(p_use.astype(jnp.int32), axis=1).astype(bool)
        valid = np.asarray(
            jnp.concatenate([jnp.ones((B, 1), bool), prefix_ok], axis=1)
        )
        m, r = np.asarray(masks), np.asarray(ref)
        assert (m[valid] == r[valid]).all(), (mode, slack)
        assert valid.sum() > B  # chains actually formed; not a vacuous pass


def test_spec_without_hetero_serves_legacy():
    """speculative.enabled without hetero_batch: the engine warns and
    serves the legacy path (no drafting, no behavior change) — config
    mistakes degrade loudly, never corrupt decode."""

    async def go():
        eng = make_engine(speculative={"enabled": True, "k": 4})
        await eng.start()
        try:
            res = await eng.generate(
                eng.tokenizer.encode("plan: compose. JSON:"), max_new_tokens=24
            )
            assert eng.grammar.walk(res.text) != eng.grammar.dead_state
            assert _spec_counters(eng) == (0.0, 0.0)
        finally:
            await eng.aclose()

    asyncio.run(go())
