"""int8 evaluation path (ADVICE r5): the README's "plan quality survives
int8 serving" claim must be reproducible from committed automation — the
committed checkpoint served through ``evaluate_planner(quantize="int8")``
and the ``eval-planner --quantize`` CLI flag that reaches it."""

import asyncio
import json
import os

import pytest

CKPT = os.path.join(
    os.path.dirname(__file__), "..", "mcpx", "models", "checkpoints",
    "planner_test_bpe.npz",
)


@pytest.mark.skipif(
    not os.path.exists(CKPT), reason="trained planner checkpoint not committed yet"
)
def test_committed_checkpoint_serves_int8_through_evaluate_planner():
    from mcpx.planner.evaluate import evaluate_planner

    out = asyncio.run(
        evaluate_planner(
            checkpoint=os.path.abspath(CKPT),
            registry_size=1000,  # the checkpoint's pinned eval protocol
            registry_seed=0,
            n_intents=4,
            quantize="int8",
        )
    )
    assert out["quantize"] == "int8"
    # The quantized engine must actually serve model plans, not fall back.
    assert out["llm_share"] > 0.0, out
    assert {"coverage", "relevance", "coherence", "score", "node_f1"} <= set(out)
    # Trained weights through int8 still clearly beat the ~0 intent match
    # random weights score (README claims 0.949; this is the loose floor a
    # 4-intent sample supports).
    assert out["score"] > 0.4, out


def test_eval_planner_cli_passes_quantize_through(monkeypatch, capsys):
    """--quantize reaches evaluate_planner verbatim (no engine run: the
    evaluation entry point is stubbed)."""
    import mcpx.planner.evaluate as evaluate_mod
    from mcpx.cli.main import main

    seen: dict = {}

    async def fake_evaluate_planner(**kwargs):
        seen.update(kwargs)
        return {"score": 1.0, "quantize": kwargs["quantize"]}

    monkeypatch.setattr(evaluate_mod, "evaluate_planner", fake_evaluate_planner)
    rc = main(
        ["eval-planner", "--quantize", "int8", "--intents", "1", "--platform", "auto"]
    )
    assert rc == 0
    assert seen["quantize"] == "int8"
    out = json.loads(capsys.readouterr().out.strip())
    assert out["quantize"] == "int8"
