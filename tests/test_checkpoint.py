"""Orbax checkpoint round-trip, including sharded restore onto a mesh."""

import jax
import numpy as np
import pytest

from mcpx.core.errors import EngineError
from mcpx.models.gemma import GemmaConfig, init_params
from mcpx.models.gemma.params import load_checkpoint, load_or_init, save_checkpoint
from mcpx.parallel import make_mesh


def test_roundtrip_and_sharded_restore(tmp_path):
    cfg = GemmaConfig(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(7))
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, params)

    restored = load_checkpoint(path, cfg)
    np.testing.assert_array_equal(
        np.asarray(restored["embed"]), np.asarray(params["embed"])
    )

    mesh = make_mesh(data=2, model=4)
    sharded = load_checkpoint(path, cfg, mesh)
    from jax.sharding import PartitionSpec as P

    assert sharded["layers"]["wq"].sharding.spec == P(None, None, "model", None)
    np.testing.assert_array_equal(
        np.asarray(sharded["layers"]["wq"]), np.asarray(params["layers"]["wq"])
    )


def test_load_or_init_random(tmp_path):
    cfg = GemmaConfig(dtype="float32")
    mesh = make_mesh(data=1, model=8)
    params, source = load_or_init(cfg, "", mesh)
    assert source == "random"
    assert params["layers"]["w_gate"].sharding.mesh.shape["model"] == 8


def test_missing_checkpoint_raises():
    cfg = GemmaConfig()
    with pytest.raises(EngineError, match="not found"):
        load_checkpoint("/nonexistent/ckpt", cfg)
