"""Scheduler subsystem unit tests (mcpx/scheduler/): token-bucket refill,
deadline/ETA shedding, fair-queuing ordering, degradation hysteresis, and
the engine's queue_stats surface."""

import asyncio
import math

import pytest

from mcpx.core.config import MCPXConfig, SchedulerConfig
from mcpx.core.errors import ConfigError
from mcpx.scheduler import (
    DegradeController,
    FairQueue,
    RequestContext,
    Scheduler,
    ShedError,
    TokenBucket,
)


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ----------------------------------------------------------- token bucket
def test_token_bucket_burst_drain_and_refill():
    clock = FakeClock()
    b = TokenBucket(rate=10.0, burst=3, clock=clock)
    assert [b.try_acquire() for _ in range(3)] == [True, True, True]
    assert not b.try_acquire()  # burst exhausted, no time passed
    assert b.eta_s() == pytest.approx(0.1)  # one token at 10/s
    clock.advance(0.05)
    assert not b.try_acquire()  # half a token
    clock.advance(0.06)
    assert b.try_acquire()
    # Refill caps at burst: a long idle gap doesn't bank unlimited tokens.
    clock.advance(100.0)
    assert b.tokens == pytest.approx(3.0)


def test_token_bucket_rejects_nonpositive_rate():
    with pytest.raises(ValueError):
        TokenBucket(rate=0.0, burst=1)


# ------------------------------------------------------------- fair queue
def test_fair_queue_quiet_tenant_jumps_hot_backlog():
    q = FairQueue()
    for i in range(5):
        q.push("hot", f"h{i}")
    q.push("cold", "c0")
    order = [q.pop() for _ in range(6)]
    # The cold tenant's single item dispatches ahead of the hot tenant's
    # backlog (entered at the global virtual time, not behind 5 tags).
    assert "c0" in order[:2], order
    assert order.count(None) == 0
    assert q.pop() is None


def test_fair_queue_weight_shares():
    q = FairQueue()
    for i in range(4):
        q.push("big", f"b{i}", weight=2.0)
        q.push("small", f"s{i}", weight=1.0)
    first6 = [q.pop() for _ in range(6)]
    n_big = sum(1 for x in first6 if x.startswith("b"))
    # weight 2 vs 1 -> a 2:1 dispatch share under contention.
    assert n_big == 4, first6


def test_fair_queue_edf_within_tenant():
    q = FairQueue()
    q.push("t", "late", deadline_at=300.0)
    q.push("t", "soon", deadline_at=100.0)
    q.push("t", "never")  # deadline-less ranks last
    q.push("t", "mid", deadline_at=200.0)
    assert [q.pop() for _ in range(4)] == ["soon", "mid", "late", "never"]


def test_fair_queue_depths():
    q = FairQueue()
    q.push("a", 1)
    q.push("a", 2)
    q.push("b", 3)
    assert q.depth() == 3
    assert q.tenant_depths() == {"a": 2, "b": 1}


# ------------------------------------------------------------ degradation
def test_degrade_hysteresis():
    clock = FakeClock()
    d = DegradeController(
        slo_s=0.1,
        degrade_threshold=0.5,  # engage above 50 ms EWMA wait
        recover_threshold=0.25,  # recover below 25 ms
        ewma_alpha=1.0,  # no smoothing: thresholds hit exactly
        min_hold_s=2.0,
        clock=clock,
    )
    assert not d.observe_wait(0.04)  # below hi: stays normal
    assert d.observe_wait(0.2)  # overload: engages
    # Pressure drops immediately — but the hold keeps the ladder engaged
    # (no flapping at the boundary).
    assert d.observe_wait(0.0)
    clock.advance(1.0)
    assert d.observe_wait(0.0)  # still inside min_hold_s
    clock.advance(1.5)
    assert not d.observe_wait(0.0)  # held long enough AND below lo: recovers
    # Between lo and hi after recovery: stays normal (hysteresis band).
    assert not d.observe_wait(0.04)


def test_degrade_requires_ordered_thresholds():
    with pytest.raises(ValueError):
        DegradeController(slo_s=1.0, degrade_threshold=0.2, recover_threshold=0.5)


# -------------------------------------------------------------- scheduler
def _sched(clock=None, **overrides) -> Scheduler:
    cfg = SchedulerConfig(enabled=True, **overrides)
    return Scheduler(cfg, None, clock=clock or FakeClock())


def test_scheduler_deadline_shed_at_enqueue():
    async def go():
        clock = FakeClock()
        s = _sched(clock, max_parallel=1)
        # A learned service time of 10s/request means a 100ms-deadline
        # request cannot possibly be served: shed synchronously.
        s._service_ewma_s = 10.0
        ctx = RequestContext(tenant="t", deadline_at=clock() + 0.1, enqueued_at=clock())
        with pytest.raises(ShedError) as ei:
            await s.acquire(ctx)
        assert ei.value.outcome == "shed_deadline"
        assert ei.value.retry_after_s >= 1.0
        assert int(ei.value.retry_after_header()) >= 1

    asyncio.run(go())


def test_scheduler_no_deadline_never_deadline_sheds():
    async def go():
        clock = FakeClock()
        s = _sched(clock, max_parallel=1)
        s._service_ewma_s = 10.0
        # deadline_at=None: remaining budget is infinite, never shed.
        slot = await s.acquire(RequestContext(tenant="t", enqueued_at=clock()))
        assert not slot.degraded
        s.release(slot)

    asyncio.run(go())


def test_scheduler_queue_cap_sheds():
    async def go():
        s = _sched(max_parallel=1, max_queue_depth=1)
        held = await s.acquire(RequestContext(tenant="t"))  # occupies the slot
        waiter = asyncio.ensure_future(s.acquire(RequestContext(tenant="t")))
        await asyncio.sleep(0)  # waiter enqueued (depth 1 = cap)
        with pytest.raises(ShedError) as ei:
            await s.acquire(RequestContext(tenant="t"))
        assert ei.value.outcome == "shed_queue"
        s.release(held)
        s.release(await waiter)

    asyncio.run(go())


def test_scheduler_dispatch_time_deadline_shed():
    """A request admitted on an optimistic ETA whose deadline expires while
    queued is shed at dispatch, not served as a corpse."""

    async def go():
        clock = FakeClock()
        s = _sched(clock, max_parallel=1)
        held = await s.acquire(RequestContext(tenant="t", enqueued_at=clock()))
        waiter = asyncio.ensure_future(
            s.acquire(
                RequestContext(tenant="t", deadline_at=clock() + 0.5, enqueued_at=clock())
            )
        )
        await asyncio.sleep(0)
        clock.advance(1.0)  # deadline passes while queued
        s.release(held)
        with pytest.raises(ShedError) as ei:
            await waiter
        assert ei.value.outcome == "shed_deadline"

    asyncio.run(go())


def test_scheduler_rate_limit_sheds_with_retry_after():
    async def go():
        clock = FakeClock()
        s = _sched(clock, rate_limit=10.0, burst=1, max_parallel=4)
        slot = await s.acquire(RequestContext(tenant="t"))
        s.release(slot)
        with pytest.raises(ShedError) as ei:
            await s.acquire(RequestContext(tenant="t"))
        assert ei.value.outcome == "shed_rate"
        assert ei.value.retry_after_s > 0

    asyncio.run(go())


def test_scheduler_service_ewma_and_engine_eta_floor():
    async def go():
        clock = FakeClock()
        eng = {"eta_s": 7.5}
        s = Scheduler(
            SchedulerConfig(enabled=True, max_parallel=1),
            None,
            engine_stats=lambda: eng,
            clock=clock,
        )
        slot = await s.acquire(RequestContext(tenant="t", enqueued_at=clock()))
        clock.advance(2.0)
        s.release(slot)
        assert s.service_ewma_s == pytest.approx(2.0)  # first sample seeds
        # Own estimate is (0+1)*2.0/1 = 2.0; engine's 7.5 floors it up.
        assert s.queue_eta_s() == pytest.approx(7.5)
        eng["eta_s"] = 0.0
        assert s.queue_eta_s() == pytest.approx(2.0)

    asyncio.run(go())


def test_scheduler_context_from_headers():
    clock = FakeClock()
    s = _sched(clock, default_deadline_ms=2000.0)
    ctx = s.context_from_headers(
        {"X-MCPX-Tenant": "acme", "X-MCPX-Deadline-Ms": "150", "X-MCPX-Priority": "4"}
    )
    assert ctx.tenant == "acme"
    assert ctx.deadline_at == pytest.approx(clock() + 0.15)
    assert ctx.weight == 4.0
    # Absent/malformed headers: defaults, never a rejection.
    ctx = s.context_from_headers({"X-MCPX-Deadline-Ms": "soon", "X-MCPX-Priority": "x"})
    assert ctx.tenant == "default"
    assert ctx.deadline_at == pytest.approx(clock() + 2.0)
    assert ctx.weight == 1.0


def test_scheduler_purges_abandoned_waiters_before_shedding():
    """Cancelled-while-queued entries (client disconnects) must not count
    as backlog: a full-of-phantoms queue purges instead of 429ing a live
    request."""
    import contextlib

    async def go():
        s = _sched(max_parallel=1, max_queue_depth=2)
        held = await s.acquire(RequestContext(tenant="t"))
        w1 = asyncio.ensure_future(s.acquire(RequestContext(tenant="t")))
        w2 = asyncio.ensure_future(s.acquire(RequestContext(tenant="t")))
        await asyncio.sleep(0)  # both enqueued: depth == cap
        w1.cancel()
        w2.cancel()
        for w in (w1, w2):
            with contextlib.suppress(asyncio.CancelledError):
                await w
        # Queue still holds the two dead entries — a live arrival purges
        # them instead of shedding shed_queue.
        live = asyncio.ensure_future(s.acquire(RequestContext(tenant="t")))
        await asyncio.sleep(0)
        s.release(held)
        slot = await live
        s.release(slot)

    asyncio.run(go())


def test_scheduler_per_tier_service_ewma():
    """Degraded (~ms) completions must not blind the primary-tier ETA
    estimate — each tier learns its own EWMA, and queue_eta_s costs the
    backlog at the tier the ladder would currently serve."""
    from mcpx.scheduler import Slot

    async def go():
        clock = FakeClock()
        s = _sched(clock, max_parallel=1)
        slot = await s.acquire(RequestContext(tenant="t", enqueued_at=clock()))
        clock.advance(1.0)
        s.release(slot)  # primary tier: 1.0s
        fake = Slot(
            ctx=RequestContext(tenant="t", enqueued_at=clock()),
            degraded=True,
            granted_at=clock(),
            queue_wait_s=0.0,
        )
        s._inflight += 1
        clock.advance(0.002)
        s.release(fake)  # degraded tier: 2ms
        assert s.service_ewma_s == pytest.approx(1.0)  # unpolluted
        assert s._degraded_ewma_s == pytest.approx(0.002)
        # Ladder off: ETA priced at the primary tier.
        assert s.queue_eta_s() == pytest.approx(1.0)
        # Ladder on: priced at the degraded tier (the tier that would
        # actually serve), so recovery-adjacent requests aren't shed on
        # the primary tier's cost.
        s._degrade.observe_wait(10.0)
        assert s.degraded
        assert s.queue_eta_s() == pytest.approx(0.002)

    asyncio.run(go())


# ---------------------------------------------------------- config wiring
def test_scheduler_config_validation():
    cfg = MCPXConfig.from_dict({"scheduler": {"enabled": True, "slo_ms": 100}})
    assert cfg.scheduler.enabled and cfg.scheduler.slo_ms == 100
    with pytest.raises(ConfigError):
        MCPXConfig.from_dict(
            {"scheduler": {"degrade_threshold": 0.2, "recover_threshold": 0.5}}
        )
    with pytest.raises(ConfigError):
        MCPXConfig.from_dict({"scheduler": {"slo_ms": 0}})
    with pytest.raises(ConfigError):
        MCPXConfig.from_dict({"scheduler": {"max_parallel": 0}})


def test_engine_queue_stats_surface():
    """queue_stats must be readable on a cold engine (scheduler attaches
    before/without start) and do fair-share ETA math on the EWMA."""
    from mcpx.engine.engine import InferenceEngine

    cfg = MCPXConfig.from_dict(
        {"model": {"size": "test", "max_seq_len": 256}, "engine": {"max_batch_size": 4}}
    )
    eng = InferenceEngine(cfg)
    st = eng.queue_stats()
    assert st == {
        # Per-path ragged-kernel engagement (ISSUE 15): resolved at
        # construction (config + head-dim probe) so a COLD engine already
        # answers; the "test" model's head_dim aligns off-TPU only via
        # interpret, which this config leaves off -> jnp route, reasoned.
        "pallas": {
            "enabled": False,
            "interpret": False,
            "reason": (
                "head_dim 32 % 128 != 0: Mosaic lane tiling rejects the "
                "kernel on hardware (engine.interpret=true lifts the "
                "constraint off-TPU)"
            ),
            "paths": {
                "decode": {
                    "engaged": False,
                    "dispatches": 0,
                    "reason": (
                        "head_dim 32 % 128 != 0: Mosaic lane tiling "
                        "rejects the kernel on hardware "
                        "(engine.interpret=true lifts the constraint "
                        "off-TPU)"
                    ),
                },
                "prefill": {
                    "engaged": False,
                    "dispatches": 0,
                    "reason": (
                        "head_dim 32 % 128 != 0: Mosaic lane tiling "
                        "rejects the kernel on hardware "
                        "(engine.interpret=true lifts the constraint "
                        "off-TPU)"
                    ),
                },
                "spec_verify": {
                    "engaged": False,
                    "dispatches": 0,
                    "reason": (
                        "head_dim 32 % 128 != 0: Mosaic lane tiling "
                        "rejects the kernel on hardware "
                        "(engine.interpret=true lifts the constraint "
                        "off-TPU)"
                    ),
                },
            },
        },
        # Radix prefix-cache scoreboard (prefix-locality admission): empty
        # tree, no lookups yet.
        "prefix_nodes": 0,
        "prefix_resident_pages": 0,
        "prefix_hit_rate": 0.0,
        "prefix_token_hit_rate": 0.0,
        # Tiered-KV additions (ISSUE 11): host-tier residency and the
        # spill/readmit/destructive tallies — zeros single-tier and on a
        # cold tiered engine alike.
        "prefix_host_pages": 0,
        "prefix_spills": 0,
        "prefix_readmits": 0,
        "prefix_destructive_evictions": 0,
        "depth": 0,
        "active": 0,
        "service_ewma_s": 0.0,
        "eta_s": 0.0,
        # Heterogeneous-batching additions: per-class backlog, head-of-line
        # age, resident stacked grammars — all zero on a cold engine.
        "depth_constrained": 0,
        "depth_free": 0,
        "hol_wait_ms": 0.0,
        "resident_grammars": 0,
        # Speculative-decoding additions: accept rates, zero until the
        # drafter has proposed anything.
        "spec_accept_rate": 0.0,
        "spec_accept_rate_constrained": 0.0,
        "spec_accept_rate_free": 0.0,
    }
    eng._ewma_service_s = 2.0
    for _ in range(5):  # 4 fit the free slab rows; 1 overflows = 1 drain
        eng._queue.put(object())
    st = eng.queue_stats()
    assert st["depth"] == 5
    assert st["eta_s"] == pytest.approx(math.ceil(1 / 4) * 2.0)
