import pytest

from mcpx.core.config import MCPXConfig
from mcpx.core.errors import ConfigError


def test_defaults_validate():
    MCPXConfig().validate()


def test_from_dict_and_unknown_key():
    cfg = MCPXConfig.from_dict({"engine": {"max_batch_size": 8}})
    assert cfg.engine.max_batch_size == 8
    with pytest.raises(ConfigError, match="unknown key"):
        MCPXConfig.from_dict({"engine": {"nope": 1}})


def test_env_overrides():
    cfg = MCPXConfig.from_env(
        {
            "MCPX_ENGINE_MAX_BATCH_SIZE": "16",
            "MCPX_ENGINE_USE_PALLAS": "false",
            "MCPX_ENGINE_TEMPERATURE": "0.7",
            "REDIS_URL": "redis://x:6379/0",
        }
    )
    assert cfg.engine.max_batch_size == 16
    assert cfg.engine.use_pallas is False
    assert cfg.engine.temperature == 0.7
    assert cfg.registry.redis_url == "redis://x:6379/0"


def test_invalid_page_size_rejected():
    with pytest.raises(ConfigError, match="power of two"):
        MCPXConfig.from_dict({"engine": {"kv_page_size": 13}})


def test_invalid_planner_kind_rejected():
    with pytest.raises(ConfigError, match="planner.kind"):
        MCPXConfig.from_dict({"planner": {"kind": "oracle"}})


def test_nested_speculative_from_dict_roundtrip():
    """engine.speculative is a NESTED dataclass: dict loading reaches one
    level deeper with the same key checking and string coercion, and
    to_dict round-trips it."""
    cfg = MCPXConfig.from_dict(
        {"engine": {"speculative": {"enabled": "true", "k": "6", "draft": "grammar"}}}
    )
    assert cfg.engine.speculative.enabled is True
    assert cfg.engine.speculative.k == 6
    assert cfg.engine.speculative.draft == "grammar"
    assert cfg.to_dict()["engine"]["speculative"] == {
        "enabled": True,
        "k": 6,
        "draft": "grammar",
    }
    with pytest.raises(ConfigError, match="engine.speculative.nope"):
        MCPXConfig.from_dict({"engine": {"speculative": {"nope": 1}}})
    # The natural YAML/JSON mistake `speculative: true` (the enable flag
    # lives INSIDE the nested object) must fail as a ConfigError at load,
    # not an AttributeError later in validate().
    with pytest.raises(ConfigError, match="engine.speculative.*object"):
        MCPXConfig.from_dict({"engine": {"speculative": True}})


def test_nested_speculative_env_overrides():
    cfg = MCPXConfig.from_env(
        {
            "MCPX_ENGINE_SPECULATIVE_ENABLED": "1",
            "MCPX_ENGINE_SPECULATIVE_K": "3",
        }
    )
    assert cfg.engine.speculative.enabled is True
    assert cfg.engine.speculative.k == 3
    assert cfg.engine.speculative.draft == "recurrent"  # untouched default


def test_invalid_speculative_rejected():
    with pytest.raises(ConfigError, match="speculative.k"):
        MCPXConfig.from_dict({"engine": {"speculative": {"k": 0}}})
    # Upper bound guards the drafter's float32 closed-form state advance
    # (2^i per window position overflows past ~127 and NaNs acceptance).
    with pytest.raises(ConfigError, match="speculative.k"):
        MCPXConfig.from_dict({"engine": {"speculative": {"k": 128}}})
    with pytest.raises(ConfigError, match="speculative.draft"):
        MCPXConfig.from_dict({"engine": {"speculative": {"draft": "oracle"}}})
