import pytest

from mcpx.core.config import MCPXConfig
from mcpx.core.errors import ConfigError


def test_defaults_validate():
    MCPXConfig().validate()


def test_from_dict_and_unknown_key():
    cfg = MCPXConfig.from_dict({"engine": {"max_batch_size": 8}})
    assert cfg.engine.max_batch_size == 8
    with pytest.raises(ConfigError, match="unknown key"):
        MCPXConfig.from_dict({"engine": {"nope": 1}})


def test_env_overrides():
    cfg = MCPXConfig.from_env(
        {
            "MCPX_ENGINE_MAX_BATCH_SIZE": "16",
            "MCPX_ENGINE_USE_PALLAS": "false",
            "MCPX_ENGINE_TEMPERATURE": "0.7",
            "REDIS_URL": "redis://x:6379/0",
        }
    )
    assert cfg.engine.max_batch_size == 16
    assert cfg.engine.use_pallas is False
    assert cfg.engine.temperature == 0.7
    assert cfg.registry.redis_url == "redis://x:6379/0"


def test_invalid_page_size_rejected():
    with pytest.raises(ConfigError, match="power of two"):
        MCPXConfig.from_dict({"engine": {"kv_page_size": 13}})


def test_invalid_planner_kind_rejected():
    with pytest.raises(ConfigError, match="planner.kind"):
        MCPXConfig.from_dict({"planner": {"kind": "oracle"}})


def test_steps_per_dispatch_roundtrip_and_bounds():
    """Fused multi-step dispatch knob (ISSUE 15): round-trips like every
    engine field, 1 = legacy per-tick cadence is legal, and out-of-range
    windows are rejected (not clamped silently)."""
    cfg = MCPXConfig.from_dict({"engine": {"steps_per_dispatch": 8}})
    assert cfg.engine.steps_per_dispatch == 8
    assert cfg.to_dict()["engine"]["steps_per_dispatch"] == 8
    MCPXConfig.from_dict({"engine": {"steps_per_dispatch": 1}}).validate()
    with pytest.raises(ConfigError, match="steps_per_dispatch"):
        MCPXConfig.from_dict({"engine": {"steps_per_dispatch": 0}})
    with pytest.raises(ConfigError, match="steps_per_dispatch"):
        MCPXConfig.from_dict({"engine": {"steps_per_dispatch": 65}})


def test_nested_speculative_from_dict_roundtrip():
    """engine.speculative is a NESTED dataclass: dict loading reaches one
    level deeper with the same key checking and string coercion, and
    to_dict round-trips it."""
    cfg = MCPXConfig.from_dict(
        {"engine": {"speculative": {"enabled": "true", "k": "6", "draft": "grammar"}}}
    )
    assert cfg.engine.speculative.enabled is True
    assert cfg.engine.speculative.k == 6
    assert cfg.engine.speculative.draft == "grammar"
    assert cfg.to_dict()["engine"]["speculative"] == {
        "enabled": True,
        "k": 6,
        "draft": "grammar",
    }
    with pytest.raises(ConfigError, match="engine.speculative.nope"):
        MCPXConfig.from_dict({"engine": {"speculative": {"nope": 1}}})
    # The natural YAML/JSON mistake `speculative: true` (the enable flag
    # lives INSIDE the nested object) must fail as a ConfigError at load,
    # not an AttributeError later in validate().
    with pytest.raises(ConfigError, match="engine.speculative.*object"):
        MCPXConfig.from_dict({"engine": {"speculative": True}})


def test_nested_speculative_env_overrides():
    cfg = MCPXConfig.from_env(
        {
            "MCPX_ENGINE_SPECULATIVE_ENABLED": "1",
            "MCPX_ENGINE_SPECULATIVE_K": "3",
        }
    )
    assert cfg.engine.speculative.enabled is True
    assert cfg.engine.speculative.k == 3
    assert cfg.engine.speculative.draft == "recurrent"  # untouched default


def test_invalid_speculative_rejected():
    with pytest.raises(ConfigError, match="speculative.k"):
        MCPXConfig.from_dict({"engine": {"speculative": {"k": 0}}})
    # Upper bound guards the drafter's float32 closed-form state advance
    # (2^i per window position overflows past ~127 and NaNs acceptance).
    with pytest.raises(ConfigError, match="speculative.k"):
        MCPXConfig.from_dict({"engine": {"speculative": {"k": 128}}})
    with pytest.raises(ConfigError, match="speculative.draft"):
        MCPXConfig.from_dict({"engine": {"speculative": {"draft": "oracle"}}})


def test_ledger_and_slo_config_roundtrip():
    """ISSUE 14 satellite: telemetry.ledger.* (nested) and the slo
    section load with key checking + string coercion, survive a to_dict
    round-trip, and validate their knobs."""
    cfg = MCPXConfig.from_dict(
        {
            "telemetry": {"ledger": {"enabled": "true", "max_tenants": "8"}},
            "slo": {
                "enabled": True,
                "bucket_s": "5",
                "windows_s": [10.0, 60.0, 120.0, 240.0],
                "objectives": [
                    {"name": "p99", "kind": "latency", "target": 0.95,
                     "threshold_ms": 250.0},
                ],
            },
            "scheduler": {"enabled": True, "burn_aware": True},
        }
    )
    assert cfg.telemetry.ledger.enabled is True
    assert cfg.telemetry.ledger.max_tenants == 8
    assert cfg.slo.bucket_s == 5.0
    round2 = MCPXConfig.from_dict(cfg.to_dict())
    assert round2.slo.objectives == cfg.slo.objectives
    assert round2.telemetry.ledger.max_tenants == 8
    assert round2.scheduler.burn_aware is True
    # Env override reaches the nested ledger section.
    env_cfg = MCPXConfig.from_env({"MCPX_TELEMETRY_LEDGER_ENABLED": "1"})
    assert env_cfg.telemetry.ledger.enabled is True
    # Unknown nested key fails at load.
    with pytest.raises(ConfigError, match="telemetry.ledger.nope"):
        MCPXConfig.from_dict({"telemetry": {"ledger": {"nope": 1}}})


def test_invalid_slo_rejected():
    with pytest.raises(ConfigError, match="objectives\\[0\\].kind"):
        MCPXConfig.from_dict(
            {"slo": {"objectives": [{"name": "x", "kind": "vibes",
                                     "target": 0.9}]}}
        )
    with pytest.raises(ConfigError, match="target"):
        MCPXConfig.from_dict(
            {"slo": {"objectives": [{"name": "x", "kind": "availability",
                                     "target": 1.5}]}}
        )
    with pytest.raises(ConfigError, match="threshold_ms"):
        MCPXConfig.from_dict(
            {"slo": {"objectives": [{"name": "x", "kind": "latency",
                                     "target": 0.9}]}}
        )
    with pytest.raises(ConfigError, match="windows_s"):
        MCPXConfig.from_dict({"slo": {"windows_s": [300.0]}})
    with pytest.raises(ConfigError, match="windows_s"):
        MCPXConfig.from_dict({"slo": {"windows_s": [300.0, 60.0]}})
    # burn_aware without the SLO engine is a wiring error, not a no-op.
    with pytest.raises(ConfigError, match="burn_aware"):
        MCPXConfig.from_dict(
            {"scheduler": {"enabled": True, "burn_aware": True}}
        )
