import pytest

from mcpx.core.config import MCPXConfig
from mcpx.core.errors import ConfigError


def test_defaults_validate():
    MCPXConfig().validate()


def test_from_dict_and_unknown_key():
    cfg = MCPXConfig.from_dict({"engine": {"max_batch_size": 8}})
    assert cfg.engine.max_batch_size == 8
    with pytest.raises(ConfigError, match="unknown key"):
        MCPXConfig.from_dict({"engine": {"nope": 1}})


def test_env_overrides():
    cfg = MCPXConfig.from_env(
        {
            "MCPX_ENGINE_MAX_BATCH_SIZE": "16",
            "MCPX_ENGINE_USE_PALLAS": "false",
            "MCPX_ENGINE_TEMPERATURE": "0.7",
            "REDIS_URL": "redis://x:6379/0",
        }
    )
    assert cfg.engine.max_batch_size == 16
    assert cfg.engine.use_pallas is False
    assert cfg.engine.temperature == 0.7
    assert cfg.registry.redis_url == "redis://x:6379/0"


def test_invalid_page_size_rejected():
    with pytest.raises(ConfigError, match="power of two"):
        MCPXConfig.from_dict({"engine": {"kv_page_size": 13}})


def test_invalid_planner_kind_rejected():
    with pytest.raises(ConfigError, match="planner.kind"):
        MCPXConfig.from_dict({"planner": {"kind": "oracle"}})
