"""The driver's graft entry points must stay importable, jittable, and
sharding-clean on the virtual 8-device mesh (conftest forces CPU x8)."""

import jax

import __graft_entry__ as graft


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.ndim == 3  # [B, T, V] logits
    assert jax.numpy.isfinite(out).all()


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


def test_dryrun_multichip_2():
    # dryrun self-arms a 2-device platform (a real re-arm, exercising the
    # clear-backends path); restore the suite's 8-device mesh afterwards.
    # Re-arming an already-latched backend needs jax_num_cpu_devices
    # (config-time, re-read on client creation) — older jax only honours
    # XLA_FLAGS, which is parsed once per process.
    import pytest

    if not hasattr(jax.config, "jax_num_cpu_devices"):
        pytest.skip("jax too old to re-arm a latched backend (no jax_num_cpu_devices)")
    try:
        graft.dryrun_multichip(2)
    finally:
        graft._force_virtual_cpu(8)
    assert len(jax.devices()) == 8
