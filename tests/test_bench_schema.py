"""Tier-1 schema gate for the bench output JSON (ISSUE 7 satellite) and
the `mcpx bench report` regression tracker.

The gate pins the NEW observability fields — the roofline block,
``pallas_reason``, and the embedded regression verdict — against
``bench._output_json`` so a later PR cannot silently drop them from the
one JSON line the driver persists. Host-side pure functions only: no
engine, no device, no timed phases."""

import io
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402  (stdlib-only module level; jax untouched)
from mcpx.cli.bench_report import (  # noqa: E402
    build_report,
    default_series,
    load_runs,
    run_report,
)


def _stats(**overrides):
    """A representative ``_run`` stats dict (the fields _output_json reads)."""
    base = {
        "plans_per_sec": 5.0,
        "p50_ms": 100.0,
        "p99_ms": 200.0,
        "open_loop_rate": 3.5,
        "sat_p50_ms": 150.0,
        "sat_p99_ms": 300.0,
        "llm_share": 1.0,
        "decode_tok_s": 80.0,
        "decode_forwards": 100,
        "tok_per_forward": 2.0,
        "prefill_tokens": 1000,
        "mfu": 0.001,
        "mfu_basis": "xla_cost_analysis",
        "roofline": {
            "basis": "xla_cost_analysis",
            "mfu_basis": "xla_cost_analysis",
            "peak_flops": 1e12,
            "peak_flops_basis": "measured_matmul",
            "peak_bytes_s": None,
            "phases": {
                "sat": {
                    "flops": 1e9,
                    "bytes_accessed": 1e8,
                    "wall_s": 1.0,
                    "achieved_flops_s": 1e9,
                    "achieved_bytes_s": 1e8,
                    "arithmetic_intensity": 10.0,
                    "mfu": 0.001,
                    "hbm_bw_util": None,
                    "bound": None,
                },
                "open": None,
            },
            "mfu_analytic": 0.0008,
            "xla_vs_analytic": 1.2,
        },
        "pallas_reason": "cpu backend: Mosaic TPU kernels cannot run — "
        "the fused-jnp reference attention serves",
        "phase_tok_per_forward": {"sat": 2.0, "open": 2.0},
        "phase_p50_ms": {"queue": 1.0, "prefill": 2.0, "decode": 3.0},
        "phase_p50_open_ms": {"queue": 1.0, "prefill": 2.0, "decode": 3.0},
        "plan_quality": {"score": 0.2},
        "backend": "cpu",
        "n_services": 1000,
        "n_requests": 16,
        "errors": 0,
        "overload": None,
        "mixed": None,
        "spec": None,
        "prefix": None,
        "tier": None,
        "flight": None,
        "ledger": None,
        "kernel": None,
        "cluster": None,
        "provenance": None,
        "pallas_paths": {
            "enabled": True,
            "interpret": True,
            "reason": None,
            "paths": {
                "decode": {"engaged": True, "dispatches": 40, "reason": None},
                "prefill": {"engaged": True, "dispatches": 12, "reason": None},
                "spec_verify": {
                    "engaged": True,
                    "dispatches": 0,
                    "reason": "idle: speculative decoding off",
                },
            },
        },
        "latency_attribution": None,
        "chaos": None,
        "grammar_fallback": {"shape_only": 0, "keys_free": 0, "typed_off": 0},
        "cache_hit_share": 0.0,
        "unique_intents": 0,
    }
    base.update(overrides)
    return base


# ------------------------------------------------------------- schema gate
def test_output_schema_carries_roofline_pallas_reason_and_verdict():
    out = bench._output_json(_stats(), {"score": 0.86}, "test")
    # The pre-existing contract fields stay.
    for key in (
        "metric", "value", "p50_ms", "llm_share", "mfu", "mfu_basis",
        "pallas", "spec_speedup", "chaos_success_rate", "grammar_fallback",
        # ISSUE 8: the prefix-reuse phase block and its promoted keys.
        "prefix", "prefill_tokens_per_request", "prefill_reduction",
        "prefix_hit_rate", "replan_p50_cold_ms", "replan_p50_warm_ms",
        # ISSUE 11: the tiered-KV phase block and its promoted keys.
        "tier", "tier_token_hit_rate", "tier_hit_ratio",
        "victim_token_hit_rate", "warm_restart_prefill_ratio",
        # ISSUE 13: the flight-recorder phase block, its promoted
        # overhead/profile keys, and the saturation warm-replan number.
        "flight", "flight_overhead_frac", "worker_profile",
        "replan_warm_sat_p50_ms",
        # ISSUE 14: the cost-ledger phase block, its promoted overhead
        # key, and the per-tenant usage-attribution block.
        "ledger", "ledger_overhead_frac", "attribution",
        # ISSUE 15: the ragged-kernel/fused-dispatch phase block, its
        # promoted cadence/speedup keys, and the per-path pallas block.
        "kernel", "decode_dispatches_per_token",
        "decode_dispatches_per_token_per_step", "fused_decode_speedup",
        "pallas_paths",
        # ISSUE 16: the cluster phase block, its promoted scaling /
        # failover / affinity / warm-rejoin keys, and the measurement
        # basis scenario dimension (ROADMAP item 4).
        "cluster", "cluster_scaling_linearity",
        "cluster_p99_one_down_ratio", "cluster_routed_token_hit_rate",
        "cluster_rr_token_hit_rate", "cluster_affinity_hit_margin",
        "cluster_warm_rejoin_prefill_ratio", "measurement_basis",
    ):
        assert key in out, key
    # ISSUE 7 fields: the roofline block…
    rf = out["roofline"]
    assert rf is not None
    assert rf["basis"] == "xla_cost_analysis"
    assert rf["mfu_basis"] == "xla_cost_analysis"
    sat = rf["phases"]["sat"]
    for key in (
        "achieved_flops_s", "achieved_bytes_s", "arithmetic_intensity",
        "mfu", "flops", "bytes_accessed",
    ):
        assert key in sat, key
    assert rf["mfu_analytic"] is not None
    # …pallas_reason…
    assert isinstance(out["pallas_reason"], str) and out["pallas_reason"]
    # …and the embedded regression verdict.
    assert isinstance(out["regression"], dict)
    assert "verdict" in out["regression"]
    json.dumps(out)  # the one-line artifact must stay JSON-serializable


def test_output_promotes_tier_phase_acceptance_keys():
    """ISSUE 11: when the tiered-KV phase ran, its acceptance numbers are
    promoted to the top level for TRACKED_METRICS regression tracking."""
    tier = {
        "working_set_ratio": 10.0,
        "tier_token_hit_rate": 0.61,
        "tier_hit_ratio": 4.2,
        "victim_token_hit_rate": 0.88,
        "warm_restart_prefill_ratio": 8.0,
        "spills": 120,
        "readmits": 80,
        "destructive_evictions": 0,
    }
    out = bench._output_json(_stats(tier=tier), None, "test")
    assert out["tier"]["working_set_ratio"] == 10.0
    assert out["tier_token_hit_rate"] == 0.61
    assert out["tier_hit_ratio"] == 4.2
    assert out["victim_token_hit_rate"] == 0.88
    assert out["warm_restart_prefill_ratio"] == 8.0
    # Skipped phase: block and promoted keys null, never absent.
    out = bench._output_json(_stats(), None, "test")
    assert out["tier"] is None and out["tier_token_hit_rate"] is None


def test_output_promotes_flight_phase_acceptance_keys():
    """ISSUE 13: when the flight phase ran, the overhead fraction and the
    worker profile block are promoted to the top level (regression
    tracking + the >=95% attribution acceptance read them there)."""
    wp = {
        "phases": {
            "dispatch": {"total_s": 1.0, "share": 0.5, "count": 10,
                         "p50_us": 100.0},
            "idle": {"total_s": 1.0, "share": 0.5, "count": 10,
                     "p50_us": 100.0},
        },
        "wall_s": 2.0,
        "attributed_s": 2.0,
        "attributed_frac": 1.0,
        "iterations": 10,
    }
    flight = {
        "requests": 64,
        "plans_per_sec_off": 50.0,
        "plans_per_sec_on": 49.5,
        "flight_overhead_frac": 0.01,
        "worker_profile": wp,
        "flight_samples": 12,
        "flight_ring_len": 12,
        "detectors": ["p99_shift"],
    }
    out = bench._output_json(_stats(flight=flight), None, "test")
    assert out["flight_overhead_frac"] == 0.01
    assert out["worker_profile"]["attributed_frac"] == 1.0
    # Skipped phase: block and promoted keys null, never absent.
    out = bench._output_json(_stats(), None, "test")
    assert out["flight"] is None and out["flight_overhead_frac"] is None
    assert out["worker_profile"] is None
    assert out["replan_warm_sat_p50_ms"] is None


def test_output_promotes_kernel_phase_acceptance_keys():
    """ISSUE 15: when the ragged-kernel/fused-dispatch phase ran, the
    dispatch cadence (fused + per-step arms) and the wall-clock guard are
    promoted to the top level for TRACKED_METRICS regression tracking,
    and the per-path pallas block rides the headline."""
    kernel = {
        "requests": 48,
        "rounds": 3,
        "steps_per_dispatch": 4,
        "per_step": {"decode_tok_s": 100.0, "dispatches_per_token": 0.26},
        "fused": {"decode_tok_s": 120.0, "dispatches_per_token": 0.06},
        "decode_dispatches_per_token": 0.06,
        "decode_dispatches_per_token_per_step": 0.26,
        "dispatch_reduction": 4.33,
        "fused_decode_speedup": 1.2,
        "interpret_parity": True,
        "cadence_parity": True,
        "pallas_paths": {"enabled": True},
    }
    out = bench._output_json(_stats(kernel=kernel), None, "test")
    assert out["kernel"]["steps_per_dispatch"] == 4
    assert out["decode_dispatches_per_token"] == 0.06
    assert out["decode_dispatches_per_token_per_step"] == 0.26
    assert out["fused_decode_speedup"] == 1.2
    assert out["pallas_paths"]["paths"]["prefill"]["engaged"] is True
    # Skipped phase: block and promoted keys null, never absent.
    out = bench._output_json(_stats(), None, "test")
    assert out["kernel"] is None
    assert out["decode_dispatches_per_token"] is None
    assert out["fused_decode_speedup"] is None


def test_output_promotes_cluster_phase_acceptance_keys():
    """ISSUE 16: when the cluster phase ran, its scaling / failover /
    affinity / warm-rejoin acceptance numbers are promoted to the top
    level for TRACKED_METRICS regression tracking."""
    cluster = {
        "basis": {"scaling": "router-sim", "warm_rejoin": "interpret-kernel"},
        "plans_per_sec": {"1": 190.0, "2": 380.0, "4": 760.0},
        "cluster_scaling_linearity": 0.98,
        "one_down": {"p99_ms_baseline": 28.0, "p99_ms_one_down": 41.0,
                     "failures": 0, "resteered": 3, "rejoin_generation": 1},
        "cluster_p99_one_down_ratio": 1.46,
        "cluster_routed_token_hit_rate": 0.79,
        "cluster_rr_token_hit_rate": 0.31,
        "cluster_affinity_hit_margin": 0.48,
        "warm_rejoin": {"prefill_ratio": 8.0, "parity_ok": True},
        "cluster_warm_rejoin_prefill_ratio": 8.0,
    }
    out = bench._output_json(_stats(cluster=cluster), None, "test")
    assert out["cluster"]["one_down"]["failures"] == 0
    assert out["cluster_scaling_linearity"] == 0.98
    assert out["cluster_p99_one_down_ratio"] == 1.46
    assert out["cluster_routed_token_hit_rate"] == 0.79
    assert out["cluster_rr_token_hit_rate"] == 0.31
    assert out["cluster_affinity_hit_margin"] == 0.48
    assert out["cluster_warm_rejoin_prefill_ratio"] == 8.0
    # Skipped phase: block and promoted keys null, never absent.
    out = bench._output_json(_stats(), None, "test")
    assert out["cluster"] is None
    assert out["cluster_scaling_linearity"] is None
    assert out["cluster_routed_token_hit_rate"] is None
    assert out["cluster_warm_rejoin_prefill_ratio"] is None


def test_output_promotes_provenance_phase_acceptance_keys():
    """ISSUE 19: when the decision-provenance phase ran, the recorder's
    overhead fraction and the /explain schema-coverage fraction are
    promoted to the top level for TRACKED_METRICS regression tracking."""
    provenance = {
        "requests": 96,
        "rounds": 3,
        "plans_per_sec_off": 50.0,
        "plans_per_sec_on": 49.7,
        "provenance_overhead_frac": 0.006,
        "explanation_coverage": 1.0,
        "decisions_per_request": 1.5,
        "records_emitted": 144,
    }
    out = bench._output_json(_stats(provenance=provenance), None, "test")
    assert out["provenance"]["decisions_per_request"] == 1.5
    assert out["provenance_overhead_frac"] == 0.006
    assert out["explanation_coverage"] == 1.0
    # Skipped phase: block and promoted keys null, never absent.
    out = bench._output_json(_stats(), None, "test")
    assert out["provenance"] is None
    assert out["provenance_overhead_frac"] is None
    assert out["explanation_coverage"] is None


def test_measurement_basis_labels_the_platform(monkeypatch):
    """ROADMAP item 4: the output JSON carries an explicit measurement
    basis — real-TPU / interpret-kernel / jnp-proxy — derived from the
    platform and the kernel route."""
    monkeypatch.setattr(bench, "_on_tpu", lambda: False)
    monkeypatch.delenv("MCPX_BENCH_PALLAS", raising=False)
    assert bench._measurement_basis() == "interpret-kernel"
    monkeypatch.setenv("MCPX_BENCH_PALLAS", "0")
    assert bench._measurement_basis() == "jnp-proxy"
    monkeypatch.delenv("MCPX_BENCH_PALLAS")
    monkeypatch.setattr(bench, "_on_tpu", lambda: True)
    assert bench._measurement_basis() == "real-TPU"
    monkeypatch.setattr(bench, "_on_tpu", lambda: False)
    out = bench._output_json(_stats(), None, "test")
    assert out["measurement_basis"] == "interpret-kernel"


def test_report_scenario_splits_on_measurement_basis():
    """A measurement-basis change (e.g. r09's jnp-proxy ->
    interpret-kernel switch) reads as a NEW scenario: prior runs on the
    old basis are excluded, not compared."""
    prior = [
        (f"a{i}", _mk_run(10.0, 100.0, measurement_basis="jnp-proxy"))
        for i in range(3)
    ]
    shifted = ("z", _mk_run(30.0, 30.0, measurement_basis="interpret-kernel"))
    rep = build_report([*prior, shifted])
    assert rep["verdict"] == "no_comparable_series"
    assert set(rep["excluded_scenario_mismatch"]) == {"a0", "a1", "a2"}
    # Same basis compares as before.
    same = ("z2", _mk_run(9.9, 101.0, measurement_basis="jnp-proxy"))
    rep = build_report([*prior, same])
    assert rep["verdict"] == "ok"
    assert set(rep["compared_against"]) == {"a0", "a1", "a2"}


def test_unwrap_derives_basis_for_pre_r10_artifacts(tmp_path):
    """Artifacts predating the measurement_basis field get it derived from
    what they recorded: TPU backend -> real-TPU; pallas + pallas_paths
    (the r09 interpreter round) -> interpret-kernel; else jnp-proxy."""
    from mcpx.cli.bench_report import _derive_basis

    assert _derive_basis(_mk_run(1.0, 1.0, backend="tpu")) == "real-TPU"
    assert _derive_basis(
        _mk_run(1.0, 1.0, pallas=True, pallas_paths={"enabled": True})
    ) == "interpret-kernel"
    assert _derive_basis(_mk_run(1.0, 1.0, pallas=False)) == "jnp-proxy"
    assert _derive_basis(_mk_run(1.0, 1.0)) == "jnp-proxy"
    # load_runs backfills through _unwrap, so scenario keying never
    # wildcards across a basis change.
    p = tmp_path / "BENCH_r05.json"
    p.write_text(json.dumps(_mk_run(10.0, 100.0, pallas=False)))
    runs = load_runs([str(p)])
    assert runs[0][1]["measurement_basis"] == "jnp-proxy"


def test_output_promotes_ledger_phase_acceptance_keys():
    """ISSUE 14: when the cost-ledger phase ran, the overhead fraction
    and the attribution block are promoted to the top level (regression
    tracking reads ledger_overhead_frac and
    attribution.wall_attributed_frac there)."""
    attribution = {
        "requests": 288,
        "wall_attributed_frac": 0.97,
        "flops_per_plan": 5.0e7,
        "decode_tokens_per_plan": 9.5,
        "flops_conserved": True,
        "tenants": {
            "acme": {"requests": 72, "decode_tokens": 700,
                     "prefill_tokens": 1500, "flops": 1.2e9,
                     "decode_ms": 9000.0},
        },
    }
    ledger = {
        "requests": 96,
        "rounds": 3,
        "plans_per_sec_off": 50.0,
        "plans_per_sec_on": 49.6,
        "ledger_overhead_frac": 0.008,
        "attribution": attribution,
        "slo": {"objectives": [
            {"name": "latency_p99", "budget_remaining": 1.0,
             "fast_burn": 0.0},
        ]},
    }
    out = bench._output_json(_stats(ledger=ledger), None, "test")
    assert out["ledger_overhead_frac"] == 0.008
    assert out["attribution"]["wall_attributed_frac"] == 0.97
    assert out["attribution"]["flops_conserved"] is True
    assert out["attribution"]["tenants"]["acme"]["requests"] == 72
    # Skipped phase: block and promoted keys null, never absent.
    out = bench._output_json(_stats(), None, "test")
    assert out["ledger"] is None and out["ledger_overhead_frac"] is None
    assert out["attribution"] is None


def test_output_roofline_never_null_even_without_accounting():
    """Acceptance: the roofline block is non-null with a LABELED fallback
    when cost accounting was unavailable — never silently absent."""
    out = bench._output_json(
        _stats(roofline=None, mfu_basis="measured_matmul"), None, "test"
    )
    assert out["roofline"] is not None
    assert out["roofline"]["basis"] == "unavailable"
    assert out["roofline"]["mfu_basis"] == "unavailable"
    assert "phases" in out["roofline"]


def test_roofline_block_from_cost_snapshots():
    """_roofline_block turns /costs snapshot deltas into per-phase achieved
    rates; a missing scrape degrades to basis='unavailable'."""

    def snap(flops, byt):
        return {"engine": {"totals": {"flops_executed": flops, "bytes_executed": byt}}}

    block = bench._roofline_block(
        snap(0.0, 0.0), snap(2e9, 4e8), snap(3e9, 6e8),
        sat_wall=2.0, open_wall=1.0,
        peak_flops=1e12, peak_flops_basis="measured_matmul", peak_bytes=None,
        mfu_analytic=0.001, analytic_flops=1e9,
    )
    assert block["basis"] == "xla_cost_analysis"
    sat, opn = block["phases"]["sat"], block["phases"]["open"]
    assert sat["achieved_flops_s"] == 1e9
    assert sat["mfu"] == 0.001
    assert sat["arithmetic_intensity"] == 5.0
    assert opn["achieved_flops_s"] == 1e9
    assert block["xla_vs_analytic"] == 2.0
    degraded = bench._roofline_block(
        None, None, None, 2.0, 1.0, 1e12, "measured_matmul", None, 0.001, 1e9
    )
    assert degraded["basis"] == "unavailable"
    assert degraded["phases"]["sat"] is None


def test_pallas_reason_covers_the_off_paths(monkeypatch):
    # CPU backend (the tier-1 platform): since ISSUE 15 the kernel serves
    # through the Pallas interpreter by default — the reason says so —
    # and MCPX_BENCH_PALLAS=0 restores the jnp proxy, reasoned.
    monkeypatch.setattr(bench, "_on_tpu", lambda: False)
    monkeypatch.delenv("MCPX_BENCH_PALLAS", raising=False)
    assert "interpret" in bench._pallas_reason()
    assert bench._pallas_on() is True
    monkeypatch.setenv("MCPX_BENCH_PALLAS", "0")
    assert "MCPX_BENCH_PALLAS=0" in bench._pallas_reason()
    assert bench._pallas_on() is False
    monkeypatch.delenv("MCPX_BENCH_PALLAS")
    # Operator override on TPU.
    monkeypatch.setattr(bench, "_on_tpu", lambda: True)
    monkeypatch.setenv("MCPX_BENCH_PALLAS", "0")
    assert "MCPX_BENCH_PALLAS=0" in bench._pallas_reason()
    # Engine hardware probe rejected the kernel.
    monkeypatch.setenv("MCPX_BENCH_PALLAS", "1")
    assert "head_dim" in bench._pallas_reason(engine_use_pallas=False)
    # Smoke artifact proved fused-jnp only.
    monkeypatch.delenv("MCPX_BENCH_PALLAS")
    monkeypatch.setattr(bench, "_smoke_artifact", lambda: {"ok": True, "pallas": False})
    assert "smoke" in bench._pallas_reason()
    # Nothing says off.
    monkeypatch.setattr(bench, "_smoke_artifact", lambda: {"ok": True, "pallas": True})
    assert bench._pallas_reason(engine_use_pallas=True) == "enabled"


# --------------------------------------------------------- regression report
def test_bench_report_over_committed_series():
    """ISSUE 7 acceptance: `mcpx bench report` over >= 2 committed
    BENCH_r*.json files produces a regression verdict."""
    runs = load_runs(default_series(REPO))
    assert len(runs) >= 2, "committed BENCH series shrank below 2 readable runs?"
    report = build_report(runs)
    assert report["verdict"] in ("ok", "regressed", "no_comparable_series")
    assert report["metrics"], "no tracked metrics evaluated"
    # The headline metric must have been comparable across the series.
    assert report["metrics"]["value"]["verdict"] in ("ok", "improved", "regressed")
    json.dumps(report)


def _mk_run(value, p50, **extra):
    return {
        "metric": "plans_per_sec", "value": value, "p50_ms": p50,
        "model": "test", "backend": "cpu", "vocab": "bpe",
        "quantize": "none", "registry": "synthetic", "n_services": 1000,
        **extra,
    }


def test_report_verdicts_bands_and_scenario_exclusion():
    runs = [
        ("r1", _mk_run(10.0, 100.0)),
        ("r2", _mk_run(10.5, 102.0)),
        ("r3", _mk_run(9.8, 98.0)),
        # A different scenario must be excluded, not averaged in.
        ("tpu", dict(_mk_run(500.0, 5.0), backend="tpu", model="2b")),
        # Latest: throughput fine (inside band), p50 3x worse (outside).
        ("r4", _mk_run(10.1, 300.0)),
    ]
    report = build_report(runs)
    assert report["verdict"] == "regressed"
    assert report["excluded_scenario_mismatch"] == ["tpu"]
    assert set(report["compared_against"]) == {"r1", "r2", "r3"}
    assert report["metrics"]["value"]["verdict"] == "ok"
    m = report["metrics"]["p50_ms"]
    assert m["verdict"] == "regressed"
    assert m["delta_frac"] > m["band_frac"]
    assert "p50_ms" in report["regressions"]
    # Improvement in the good direction reads as improved, not regressed.
    runs[-1] = ("r4", _mk_run(20.0, 99.0))
    report = build_report(runs)
    assert report["verdict"] == "ok"
    assert report["metrics"]["value"]["verdict"] == "improved"


def test_absolute_noise_floor_for_near_zero_fractions():
    """flight/ledger overhead and deadline-overrun share are paired
    differences with a true value of ~0: when both the latest value and
    the prior median sit inside the metric's absolute floor, the verdict
    reads ok no matter how large the RELATIVE delta looks (r08..r10 kept
    flagging 0.018 -> 0.054 as a 3x regression). A value that escapes
    the floor is judged by the normal band."""
    from mcpx.cli.bench_report import NOISE_FLOORS, render_text

    prior = [
        ("a", _mk_run(10.0, 100.0, flight_overhead_frac=-0.0183)),
        ("b", _mk_run(10.1, 101.0, flight_overhead_frac=0.0026)),
        ("c", _mk_run(9.9, 99.0, flight_overhead_frac=0.0173)),
    ]
    inside = ("z", _mk_run(10.0, 100.0, flight_overhead_frac=0.0544))
    report = build_report([*prior, inside])
    m = report["metrics"]["flight_overhead_frac"]
    assert m["verdict"] == "ok"
    assert m["floor_abs"] == NOISE_FLOORS["flight_overhead_frac"]
    assert "flight_overhead_frac" not in report["regressions"]
    assert "floor=±0.06 abs" in render_text(report)
    # 12% measured overhead is NOT jitter: it escapes the floor and the
    # near-zero median makes the relative delta blow past any band.
    escaped = ("z", _mk_run(10.0, 100.0, flight_overhead_frac=0.12))
    report = build_report([*prior, escaped])
    assert report["metrics"]["flight_overhead_frac"]["verdict"] == "regressed"
    assert "flight_overhead_frac" in report["regressions"]


def test_report_missing_metric_is_flagged_when_it_vanishes():
    prior = [("a", _mk_run(10.0, 100.0, mfu=0.01)) for _ in range(3)]
    latest = ("z", _mk_run(10.0, 100.0))  # mfu dropped
    report = build_report([*prior, latest])
    assert report["metrics"]["mfu"]["verdict"] == "missing"
    assert report["metrics"]["mfu"]["previous_median"] == 0.01
    # Surfaced in the top-level missing list, but NOT a regression verdict:
    # optional phases null their metrics legitimately; dropped FIELDS are
    # the schema gate's business.
    assert "mfu" in report["missing"]
    assert report["verdict"] == "ok"


def test_mfu_compared_only_within_matching_basis():
    """A measurement-basis change (analytic -> xla_cost_analysis) must not
    read as a performance regression/improvement: mfu only compares
    against prior runs with the SAME mfu_basis."""
    prior = [
        (f"a{i}", _mk_run(10.0, 100.0, mfu=0.005, mfu_basis="measured_matmul"))
        for i in range(3)
    ]
    shifted = ("z", _mk_run(10.0, 100.0, mfu=0.02, mfu_basis="xla_cost_analysis"))
    rep = build_report([*prior, shifted])
    assert rep["metrics"]["mfu"]["verdict"] == "new"  # no cross-basis priors
    assert rep["metrics"]["mfu"]["basis"] == "xla_cost_analysis"
    same_basis = ("z2", _mk_run(10.0, 100.0, mfu=0.002, mfu_basis="measured_matmul"))
    rep = build_report([*prior, same_basis])
    assert rep["metrics"]["mfu"]["verdict"] == "regressed"


def test_run_report_cli_exit_codes(tmp_path):
    p1 = tmp_path / "BENCH_r01.json"
    p2 = tmp_path / "BENCH_r02.json"
    p1.write_text(json.dumps(_mk_run(10.0, 100.0)))
    p2.write_text(json.dumps(_mk_run(10.0, 500.0)))  # p50 regressed 5x
    out = io.StringIO()
    assert run_report([str(p1), str(p2)], fmt="json", out=out) == 0
    payload = json.loads(out.getvalue())
    assert payload["verdict"] == "regressed"
    assert run_report(
        [str(p1), str(p2)], fail_on_regression=True, out=io.StringIO()
    ) == 1
    # Fewer than two readable artifacts is a usage error, not a crash.
    assert run_report([str(p1)], out=io.StringIO()) == 2
    # Driver-wrapper artifacts ({"parsed": ...}) unwrap transparently.
    p3 = tmp_path / "BENCH_r03.json"
    p3.write_text(json.dumps({"rc": 0, "parsed": _mk_run(11.0, 101.0)}))
    out = io.StringIO()
    assert run_report([str(p1), str(p3)], fmt="json", out=out) == 0
    assert json.loads(out.getvalue())["latest"] == "BENCH_r03.json"


def test_cli_subcommand_wiring(tmp_path):
    from mcpx.cli.main import main

    p1 = tmp_path / "a.json"
    p2 = tmp_path / "b.json"
    p1.write_text(json.dumps(_mk_run(10.0, 100.0)))
    p2.write_text(json.dumps(_mk_run(10.2, 101.0)))
    assert main(["bench", "report", str(p1), str(p2)]) == 0
    assert main(["bench", "report", "--format", "json", str(p1), str(p2)]) == 0


def test_regression_block_embedded_against_repo_series():
    out = bench._output_json(_stats(), None, "test")
    reg = out["regression"]
    # The repo ships >= 2 comparable CPU-proxy rounds, so the embedded
    # verdict must have actually compared something.
    assert reg["verdict"] in ("ok", "regressed")
    assert reg["compared_against"]
