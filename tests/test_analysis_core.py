"""The interprocedural analysis core (mcpx/analysis/{callgraph,dataflow,
project}.py): call-graph construction and resolution (golden snapshot over
a fixture package), backward reachability semantics (spawn edges excluded,
marked terminals), type inference plumbing, and taint-reachability
property tests over synthesized call chains of varying depth."""

import pathlib
import textwrap

import pytest

from mcpx.analysis import scan_paths
from mcpx.analysis.core import FileContext, _relpath, iter_py_files
from mcpx.analysis.project import ProjectContext

REPO = pathlib.Path(__file__).resolve().parent.parent
CGPKG = REPO / "tests" / "fixtures" / "lint" / "cgpkg"
PREFIX = "tests.fixtures.lint.cgpkg."


def _project(paths, root):
    ctxs = [
        FileContext(p, _relpath(p, root), p.read_text())
        for p in iter_py_files(paths)
    ]
    return ProjectContext(ctxs, root)


# ------------------------------------------------------------- call graph
def test_callgraph_golden_snapshot():
    """The full edge set over the fixture package: direct method calls,
    an imported helper, a Thread spawn and a create_task spawn — and the
    inner `self.handle()` of `create_task(self.handle())` does NOT double
    as a plain call edge (its body runs in the spawned context)."""
    proj = _project([CGPKG], REPO)
    edges = [
        (c[len(PREFIX):], e[len(PREFIX):], k)
        for c, e, k in proj.callgraph().summary()
    ]
    assert edges == [
        ("mainmod.Runner._loop", "mainmod.Runner.tick", "call"),
        ("mainmod.Runner.handle", "mainmod.Runner.tick", "call"),
        ("mainmod.Runner.serve", "mainmod.Runner.handle", "spawn"),
        ("mainmod.Runner.start", "mainmod.Runner._loop", "spawn"),
        ("mainmod.Runner.tick", "util.helper", "call"),
    ]


def test_callgraph_roots_exclude_spawn_edges():
    """Backward reachability walks plain call edges only: `tick` is
    reached from `_loop` (whose Thread-spawn in-edge does not count — it
    is its own terminal) and `handle` (spawned by create_task, likewise
    terminal). `serve` never appears: its only edge to `handle` is a
    spawn."""
    proj = _project([CGPKG], REPO)
    cg = proj.callgraph()
    roots = {q[len(PREFIX):] for q in cg.roots_of(PREFIX + "mainmod.Runner.tick")}
    assert roots == {"mainmod.Runner._loop", "mainmod.Runner.handle"}
    # a caller-less function is its own root
    assert cg.roots_of(PREFIX + "util.unused") == frozenset(
        {PREFIX + "util.unused"}
    )


def test_index_resolves_types_and_imports():
    proj = _project([CGPKG], REPO)
    index = proj.index
    # relative import resolved to the sibling module's function
    mod = index.modules[PREFIX.rstrip(".") + ".mainmod"]
    assert mod.imports["helper"] == PREFIX + "util.helper"
    # constructor-assignment attr typing: Runner().count has no class, but
    # Runner itself resolves as a class of the module
    assert PREFIX + "mainmod.Runner" in index.classes


# ----------------------------------------------- dataflow reachability
def _chain_source(n: int, *, sanitize: bool) -> str:
    """A payload field flowing through ``n`` async helpers into a jitted
    static arg; with ``sanitize`` the first hop quantizes it."""
    lines = [
        "import jax",
        "import jax.numpy as jnp",
        "",
        "",
        "def _impl(x, k):",
        "    return x[:k]",
        "",
        "",
        "step = jax.jit(_impl, static_argnames=('k',))",
        "",
        "",
        "def to_bucket(v):",
        "    return 8 if v <= 8 else 64",
        "",
        "",
        "class Req:  # mcpx: request-payload",
        "    n: int",
        "",
    ]
    entry = "to_bucket(req.n)" if sanitize else "req.n"
    lines += [
        "",
        "async def handle(req: Req):",
        f"    await f0({entry})",
        "",
    ]
    for i in range(n):
        callee = f"f{i + 1}" if i + 1 < n else None
        lines += ["", f"async def f{i}(v):"]
        if callee is not None:
            lines.append(f"    await {callee}(v)")
        else:
            lines.append("    step(jnp.zeros((16,)), v)")
    return "\n".join(lines) + "\n"


@pytest.mark.parametrize("depth", [1, 2, 3, 4])
def test_taint_reaches_static_arg_through_n_hops(tmp_path, depth):
    p = tmp_path / "chain.py"
    p.write_text(_chain_source(depth, sanitize=False))
    res = scan_paths([p], root=tmp_path, rules=["jit-contract"])
    assert len(res.findings) == 1, [f.render() for f in res.findings]
    assert "Req.n" in res.findings[0].message
    assert "static arg 'k'" in res.findings[0].message


@pytest.mark.parametrize("depth", [1, 3])
def test_bucketing_sanitizes_at_any_depth(tmp_path, depth):
    p = tmp_path / "chain.py"
    p.write_text(_chain_source(depth, sanitize=True))
    res = scan_paths([p], root=tmp_path, rules=["jit-contract"])
    assert res.findings == []


def test_taint_flows_through_heap_attributes(tmp_path):
    """The engine's latch shape: a payload field stored onto an object
    attribute in one method, read back in another, and fed to a static
    arg — provenance survives the heap hop."""
    p = tmp_path / "latch.py"
    p.write_text(
        textwrap.dedent(
            """
            import jax
            import jax.numpy as jnp


            def _impl(x, k):
                return x[:k]


            step = jax.jit(_impl, static_argnames=('k',))


            class Req:  # mcpx: request-payload
                n: int


            class Slab:
                def __init__(self):
                    self.width = 0


            class Engine:
                def __init__(self):
                    self.slab = Slab()

                def admit(self, r: Req):
                    self.slab.width = r.n

                def dispatch(self):
                    step(jnp.zeros((16,)), self.slab.width)
            """
        )
    )
    res = scan_paths([p], root=tmp_path, rules=["jit-contract"])
    assert len(res.findings) == 1
    assert "Req.n" in res.findings[0].message


def test_unrelated_class_attr_does_not_borrow_taint(tmp_path):
    """Class-keyed heap cells: a tainted `Slab.width` must not taint
    `Config.width` reads — the false-positive shape that would poison
    warmup dispatches fed from config."""
    p = tmp_path / "split.py"
    p.write_text(
        textwrap.dedent(
            """
            import jax
            import jax.numpy as jnp


            def _impl(x, k):
                return x[:k]


            step = jax.jit(_impl, static_argnames=('k',))


            class Req:  # mcpx: request-payload
                n: int


            class Slab:
                def __init__(self):
                    self.width = 0


            class Config:
                def __init__(self):
                    self.width = 8


            class Engine:
                def __init__(self):
                    self.slab = Slab()
                    self.cfg = Config()

                def admit(self, r: Req):
                    self.slab.width = r.n

                def warmup(self):
                    step(jnp.zeros((16,)), self.cfg.width)
            """
        )
    )
    res = scan_paths([p], root=tmp_path, rules=["jit-contract"])
    assert res.findings == []
