"""Ragged mixed-phase kernel + fused multi-step dispatch (ISSUE 15):
seeded kernel-vs-reference property coverage over mixed row batches,
compile-count invariance across ragged phase mixes via the cost-registry
sentinel, and fused-vs-per-step greedy byte parity through the live
engine (mid-window retirement, replan pin, spill/readmit interleave)."""

import asyncio
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mcpx.core.config import MCPXConfig
from mcpx.engine.kernels.paged_attention import (
    ragged_paged_attention,
    ragged_paged_attention_reference,
)


# --------------------------------------------------- kernel property test
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_ragged_kernel_matches_reference_over_mixed_batches(seed):
    """Seeded property test: one launch serving a MIXED batch — rows with
    q_len = S (suffix prefill), q_len = 1 (plain decode), 1 < q_len < S
    (spec-verify windows) and q_len = 0 (idle) — agrees with the jnp
    reference everywhere, INCLUDING the zeroed pad/idle positions, over
    random page tables and start offsets."""
    rng = random.Random(seed)
    B, S = 6, 5
    K, G, hd, psz = 2, 2, 16, 4
    p_max = 12
    n_pages = B * p_max + 2
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, S, K, G, hd), jnp.float32)
    kp = jax.random.normal(ks[1], (K, 2, n_pages, psz, hd), jnp.float32)
    vp = jax.random.normal(ks[2], (K, 2, n_pages, psz, hd), jnp.float32)
    table = np.zeros((B, p_max), np.int32)
    used = {0}
    for b in range(B):
        for i in range(p_max):
            p = rng.choice([x for x in range(1, n_pages) if x not in used])
            used.add(p)
            table[b, i] = p
    # The mix: every row class the engine dispatches, plus random fill.
    q_lens = [S, 1, rng.randint(2, S - 1), 0, rng.randint(0, S), 1]
    starts = [
        rng.randint(0, p_max * psz - max(1, q_lens[b]) - 1) for b in range(B)
    ]
    table_j = jnp.asarray(table)
    starts_j = jnp.asarray(starts, jnp.int32)
    q_lens_j = jnp.asarray(q_lens, jnp.int32)
    for layer in (0, 1):
        ref = ragged_paged_attention_reference(
            q, kp, vp, table_j, starts_j, q_lens_j, layer
        )
        out = ragged_paged_attention(
            q, kp, vp, table_j, starts_j, q_lens_j, layer, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )
        # The pad contract explicitly: zeros past each row's q_len.
        for b in range(B):
            assert np.all(np.asarray(out[b, q_lens[b]:]) == 0.0), (layer, b)


def test_ragged_idle_rows_stream_zero_pages_and_output_zeros():
    """The idle-row contract, tested at the only level it CAN be tested:
    from the outputs alone, streamed-then-masked and never-streamed are
    indistinguishable (the masking's correctness argument), so the page
    walk bound is a factored-out pure function — an idle row (q_len = 0)
    streams exactly zero pages however deep its frozen history, while
    live rows stream through their last visible position clamped to the
    table width. Plus the end-to-end half: idle rows output zeros."""
    from mcpx.engine.kernels.paged_attention import _ragged_n_pages

    n = _ragged_n_pages(
        jnp.asarray([512, 5, 5, 19, 0]),  # frozen-deep idle, decode, ...
        jnp.asarray([0, 1, 0, 4, 1]),
        4,
        8,
    )
    # Without the q_len gate the first/third rows would stream their
    # whole dead history (128 / 2 pages of DMA per head per layer per
    # forward — and done rows ride many forwards in a fused window).
    assert list(np.asarray(n)) == [0, 2, 0, 6, 1]

    B, S, K, G, hd, psz, p_max = 2, 3, 1, 2, 16, 4, 3
    n_pages = p_max + 1
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    q = jax.random.normal(ks[0], (B, S, K, G, hd), jnp.float32)
    kp = jax.random.normal(ks[1], (K, 1, n_pages, psz, hd), jnp.float32)
    vp = jax.random.normal(ks[2], (K, 1, n_pages, psz, hd), jnp.float32)
    table = jnp.asarray([[1, 2, 3], [1, 2, 3]], jnp.int32)
    starts = jnp.asarray([2, 5], jnp.int32)
    q_lens = jnp.asarray([3, 0], jnp.int32)
    out = ragged_paged_attention(
        q, kp, vp, table, starts, q_lens, 0, interpret=True
    )
    ref = ragged_paged_attention_reference(q, kp, vp, table, starts, q_lens, 0)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    assert np.all(np.asarray(out[1]) == 0.0)


# ------------------------------------------------------------ engine-level
def _engine_cfg(**overrides):
    eng = {
        "max_batch_size": 4,
        "max_decode_len": 24,
        "kv_page_size": 16,
        "max_pages_per_seq": 16,
        "temperature": 0.0,
        # The CPU proxy serves the SAME kernel body TPUs run, via the
        # Pallas interpreter (the ISSUE 15 headline contract).
        "use_pallas": True,
        "interpret": True,
    }
    eng.update(overrides)
    return MCPXConfig.from_dict(
        {"model": {"size": "test", "max_seq_len": 256}, "engine": eng}
    )


def _mk(**overrides):
    from mcpx.engine.engine import InferenceEngine

    return InferenceEngine(_engine_cfg(**overrides))


def test_compile_count_invariant_across_ragged_mixes():
    """Cost-registry sentinel gate: after one warm pass per executable,
    serving any prefill/decode mix — fresh prompts, deep radix repeats
    (ragged suffix offsets), short-budget rows retiring mid-window next
    to long-budget rows — compiles NOTHING new. Raggedness (q_lens,
    start offsets, page tables) is data, so the executable population is
    a function of bucket geometry alone."""

    async def go():
        eng = _mk()
        await eng.start()
        try:
            tok = eng.tokenizer
            header = "Compose a DAG.\nServices:\n"
            prompts = [
                tok.encode(header + f"svc-{i} in:a out:b\nIntent: t{i}\nJSON:")
                for i in range(3)
            ]
            # Warm pass: compiles full prefill, suffix prefill (repeat),
            # admit/merge, segment for the A=1 cohort bucket.
            for p in prompts:
                await eng.generate(p, max_new_tokens=12, constrained=False)
            await eng.generate(prompts[0], max_new_tokens=12, constrained=False)
            snap0 = {
                name: e["compiles"]
                for name, e in eng.costs.snapshot(materialize=False)[
                    "executables"
                ].items()
            }
            # The ragged mixes: repeats at three different matched
            # offsets, a novel tail (different suffix length), and
            # budgets from 1 to the cap (mid-window retirement).
            for i, p in enumerate(prompts):
                await eng.generate(
                    p, max_new_tokens=1 + 7 * i, constrained=False
                )
            novel = tok.encode(header + "svc-9 in:x out:y\nIntent: n\nJSON:")
            await eng.generate(novel, max_new_tokens=3, constrained=False)
            snap1 = {
                name: e["compiles"]
                for name, e in eng.costs.snapshot(materialize=False)[
                    "executables"
                ].items()
            }
            assert snap1 == snap0, (snap0, snap1)
        finally:
            await eng.aclose()

    asyncio.run(go())


def test_fused_vs_per_step_greedy_byte_parity_with_mid_window_retirement():
    """The fused window is a pure cadence lever: the SAME greedy requests
    — staggered budgets so rows retire mid-window while neighbours keep
    decoding, plus a replan pin held across serving — produce
    byte-identical tokens under steps_per_dispatch=1 and =4, and the
    fused engine issues measurably fewer decode dispatches."""

    async def go():
        per_step = _mk(steps_per_dispatch=1)
        fused = _mk(steps_per_dispatch=4)
        await per_step.start()
        await fused.start()
        try:
            tok = per_step.tokenizer
            header = "Fused parity header padding words.\n"
            prompts = [
                tok.encode(header + f"intent {i}: compose. JSON:")
                for i in range(6)
            ]
            budgets = [2, 19, 7, 23, 1, 12]  # retire at different windows

            async def serve(eng):
                pin = await eng.pin_prefix(prompts[0])  # replan-pin shape
                rs = await asyncio.gather(
                    *(
                        eng.generate(
                            p,
                            max_new_tokens=b,
                            constrained=False,
                            temperature=0.0,
                        )
                        for p, b in zip(prompts, budgets)
                    )
                )
                eng.unpin_prefix(pin)
                return [r.token_ids for r in rs]

            a = await serve(per_step)
            b = await serve(fused)
            assert a == b
            # Cadence actually moved: fewer dispatches per decoded token.
            ps = per_step.pallas_paths()["paths"]["decode"]["dispatches"]
            fu = fused.pallas_paths()["paths"]["decode"]["dispatches"]
            ps_tok = per_step.metrics.decode_tokens._value.get()
            fu_tok = fused.metrics.decode_tokens._value.get()
            assert ps_tok == fu_tok > 0
            assert fu < ps, (fu, ps)
        finally:
            await per_step.aclose()
            await fused.aclose()

    asyncio.run(go())


def test_fused_parity_survives_spill_readmit_interleave():
    """Fused dispatch under the tiered KV cache: repeats whose matched
    runs spill to host RAM and re-admit between windows still decode
    byte-identically to the per-step cadence."""

    async def go():
        def tiered(steps):
            return _mk(
                steps_per_dispatch=steps,
                max_decode_len=8,
                prefix_cache_entries=64,
                kv_tier={"enabled": True, "host_mb": 64.0},
            )

        eng1 = tiered(1)
        eng4 = tiered(4)
        await eng1.start()
        await eng4.start()
        try:
            tok = eng1.tokenizer
            prompts = [
                tok.encode(f"tier probe {i}: " + "wxyz " * 28)[:128]
                for i in range(8)
            ]

            async def serve(eng):
                outs = []
                for _ in range(2):  # round 2 re-admits round 1's spills
                    for p in prompts:
                        r = await eng.generate(
                            p,
                            max_new_tokens=8,
                            constrained=False,
                            temperature=0.0,
                        )
                        outs.append(r.token_ids)
                return outs

            a = await serve(eng1)
            b = await serve(eng4)
            assert a == b
            tier = eng4.prefix_cache_stats()["tier"]
            assert tier["spills"] > 0, tier
        finally:
            await eng1.aclose()
            await eng4.aclose()

    asyncio.run(go())
