"""Ring attention / sequence parallelism on the 8-device virtual CPU mesh:
golden parity with dense causal attention and with dense prefill
(SURVEY.md §4.3 — multi-chip semantics without a cluster)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mcpx.models.gemma.config import GemmaConfig
from mcpx.models.gemma.model import _attend, init_params, prefill, init_kv_cache
from mcpx.parallel.mesh import make_mesh
from mcpx.parallel.ring_attention import ring_attention, ring_prefill
from mcpx.utils.backend import mesh_context


def dense_reference(q, k, v, seq_lens):
    """model._attend with the causal + right-padding mask ring builds."""
    B, T = q.shape[0], q.shape[1]
    pos = jnp.arange(T)
    mask = (pos[None, None, :] <= pos[None, :, None]) & (
        pos[None, None, :] < seq_lens[:, None, None]
    )
    mask = jnp.broadcast_to(mask, (B, T, T))
    return _attend(q, k, v, mask)


@pytest.mark.parametrize(
    "mesh_kw,B,T,K,G",
    [
        ({"seq": 8}, 2, 64, 2, 2),  # pure SP
        ({"seq": 4, "model": 2}, 2, 32, 2, 1),  # SP x TP(heads), MQA-ish
        ({"data": 2, "seq": 4}, 4, 32, 1, 3),  # DP x SP, GQA
    ],
)
def test_ring_matches_dense(mesh_kw, B, T, K, G):
    mesh = make_mesh(**mesh_kw)
    hd = 8
    key = jax.random.PRNGKey(0)
    kq, kk, kv_, kl = jax.random.split(key, 4)
    q = jax.random.normal(kq, (B, T, K, G, hd), jnp.float32)
    k = jax.random.normal(kk, (B, T, K, hd), jnp.float32)
    v = jax.random.normal(kv_, (B, T, K, hd), jnp.float32)
    # Ragged valid lengths, including one full and one very short row.
    seq_lens = jnp.asarray(
        np.concatenate([[T, 3], jax.random.randint(kl, (max(B - 2, 0),), 1, T + 1)])[:B],
        jnp.int32,
    )

    ref = dense_reference(q, k, v, seq_lens)
    with mesh_context(mesh):
        out = jax.jit(lambda *a: ring_attention(*a, mesh))(q, k, v, seq_lens)

    # Compare only valid query positions (padded queries are don't-care).
    valid = np.arange(T)[None, :] < np.asarray(seq_lens)[:, None]
    np.testing.assert_allclose(
        np.asarray(out)[valid], np.asarray(ref)[valid], rtol=2e-5, atol=2e-5
    )


def test_ring_prefill_matches_dense_prefill():
    cfg = GemmaConfig.named("test")
    mesh = make_mesh(seq=8)
    B, T = 2, 64
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, 255)
    seq_lens = jnp.asarray([T, 37], jnp.int32)

    ref_logits, ref_cache = jax.jit(prefill, static_argnums=1)(
        params, cfg, tokens, seq_lens, init_kv_cache(cfg, B, T)
    )
    with mesh_context(mesh):
        logits, cache = jax.jit(
            lambda p, t, sl: ring_prefill(p, cfg, t, sl, mesh)
        )(params, tokens, seq_lens)

    valid = np.arange(T)[None, :] < np.asarray(seq_lens)[:, None]
    # bf16 params: reduction-order differences between the masked-dense and
    # online-softmax paths leave ~bf16-eps absolute noise on the logits.
    np.testing.assert_allclose(
        np.asarray(logits)[valid], np.asarray(ref_logits)[valid], rtol=2e-2, atol=7e-2
    )
    # KV caches must agree on valid positions too (they feed later decode).
    for name in ("k", "v"):
        got = np.asarray(cache[name], np.float32)[:, valid]
        want = np.asarray(ref_cache[name], np.float32)[:, valid]
        np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_ring_requires_seq_axis_and_divisibility():
    from mcpx.core.errors import ConfigError

    q = jnp.zeros((1, 8, 1, 1, 4))
    k = jnp.zeros((1, 8, 1, 4))
    sl = jnp.asarray([8], jnp.int32)
    with pytest.raises(ConfigError):
        ring_attention(q, k, k, sl, make_mesh(data=2, model=4))
    mesh = make_mesh(seq=8)
    with pytest.raises(ConfigError):
        ring_attention(q[:, :6], k[:, :6], k[:, :6], sl, mesh)
