"""API-surface integration tests: aiohttp TestClient against the full app
with fake in-process microservices (SURVEY.md §4.4)."""

import asyncio

from aiohttp.test_utils import TestClient, TestServer

from mcpx.core.config import MCPXConfig
from mcpx.orchestrator.transport import RouterTransport
from mcpx.server.app import build_app
from mcpx.server.factory import build_control_plane

from tests.helpers import FakeService, make_transport


def make_app(*services: FakeService, config=None, planner=None):
    transport = RouterTransport(local=make_transport(*services))
    cp = build_control_plane(config or MCPXConfig(), transport=transport, planner=planner)
    return cp, build_app(cp)


async def with_client(app, fn):
    client = TestClient(TestServer(app))
    await client.start_server()
    try:
        return await fn(client)
    finally:
        await client.close()


def seed_services(cp, *records):
    async def go():
        for r in records:
            await cp.registry.put(r)

    return go()


def test_full_flow_plan_execute():
    from mcpx.registry import ServiceRecord

    search = FakeService("search", result={"document": "the doc"})
    summarize = FakeService("summarize", result={"summary": "short"})

    async def go():
        cp, app = make_app(search, summarize)
        await cp.registry.put(
            ServiceRecord(
                name="search",
                endpoint="local://search",
                description="search documents by query",
                input_schema={"query": "str"},
                output_schema={"document": "str"},
            )
        )
        await cp.registry.put(
            ServiceRecord(
                name="summarize",
                endpoint="local://summarize",
                description="summarize a document",
                input_schema={"document": "str"},
                output_schema={"summary": "str"},
            )
        )

        async def drive(client):
            # /plan (reference wire: PlanRequest{intent} -> PlanResponse{graph})
            r = await client.post("/plan", json={"intent": "search documents and summarize"})
            assert r.status == 200
            plan_body = await r.json()
            assert "graph" in plan_body and plan_body["explanation"]
            # /execute with the planned graph
            r = await client.post(
                "/execute", json={"graph": plan_body["graph"], "payload": {"query": "q"}}
            )
            assert r.status == 200
            body = await r.json()
            assert body["status"] == "ok"
            assert body["results"]["summarize"] == {"summary": "short"}
            assert body["trace"]["nodes"]
            # /plan_and_execute end to end
            r = await client.post(
                "/plan_and_execute",
                json={"intent": "search documents and summarize", "payload": {"query": "q"}},
            )
            assert r.status == 200
            body = await r.json()
            assert body["status"] == "ok"
            assert body["replans"] == 0

        await with_client(app, drive)

    asyncio.run(go())


def test_validation_errors():
    async def go():
        cp, app = make_app()

        async def drive(client):
            r = await client.post("/plan", json={"intent": ""})
            assert r.status == 400
            r = await client.post("/plan", data=b"{not json")
            assert r.status == 400
            r = await client.post("/execute", json={"graph": {"nodes": [{"name": "a"}], "edges": [{"from": "a", "to": "ghost"}]}})
            assert r.status == 422
            body = await r.json()
            assert any("ghost" in p for p in body["problems"])
            # Empty registry -> planning fails cleanly.
            r = await client.post("/plan", json={"intent": "do something"})
            assert r.status == 422

        await with_client(app, drive)

    asyncio.run(go())


def test_service_crud_and_observability():
    async def go():
        cp, app = make_app()

        async def drive(client):
            record = {
                "name": "svc-a",
                "endpoint": "local://svc-a",
                "input_schema": {"x": "str"},
                "output_schema": {"y": "str"},
            }
            r = await client.post("/services", json=record)
            assert r.status == 201
            r = await client.get("/services")
            body = await r.json()
            assert [s["name"] for s in body["services"]] == ["svc-a"]
            assert body["version"] == 1
            r = await client.get("/services/svc-a")
            assert (await r.json())["endpoint"] == "local://svc-a"
            r = await client.delete("/services/svc-a")
            assert r.status == 200
            r = await client.get("/services/svc-a")
            assert r.status == 404
            # Observability endpoints.
            r = await client.get("/healthz")
            assert (await r.json())["status"] == "ok"
            r = await client.get("/metrics")
            text = await r.text()
            assert "mcpx_requests_total" in text
            r = await client.get("/telemetry")
            assert r.status == 200

        await with_client(app, drive)

    asyncio.run(go())


def test_cache_endpoint_combines_plan_and_prefix_stats():
    """GET /cache (ISSUE 8 satellite): plan-cache hit accounting readable
    as JSON instead of scrape-only counters; the prefix block is null on a
    heuristic control plane (no engine) and reports enabled/nodes/hit_rate
    when an engine is attached."""

    async def go():
        cp, app = make_app()

        async def drive(client):
            await client.post(
                "/services",
                json={
                    "name": "svc-a",
                    "endpoint": "local://svc-a",
                    "input_schema": {"x": "str"},
                    "output_schema": {"y": "str"},
                },
            )
            r = await client.post("/plan", json={"intent": "use svc-a"})
            assert r.status == 200
            r = await client.post("/plan", json={"intent": "use svc-a"})
            assert r.status == 200
            r = await client.get("/cache")
            assert r.status == 200
            body = await r.json()
            pc = body["plan_cache"]
            assert pc["hits"] == 1 and pc["misses"] == 1
            assert pc["entries"] == 1 and pc["hit_rate"] == 0.5
            # Heuristic planner: no engine, no prefix tree.
            assert body["prefix_cache"] is None

        await with_client(app, drive)

        # With an engine-shaped planner the prefix block surfaces.
        class EngineStub:
            def prefix_cache_stats(self):
                return {"enabled": True, "nodes": 3, "hit_rate": 0.75}

        class PlannerStub:
            engine = EngineStub()

            async def plan(self, intent, context):
                raise AssertionError("unused")

        cp.planner = PlannerStub()
        assert cp.cache_stats()["prefix_cache"]["nodes"] == 3

    asyncio.run(go())


def test_cache_endpoint_surfaces_tier_and_governor_stats():
    """GET /cache (ISSUE 11 satellite): with the tiered KV cache armed the
    prefix block carries the host-tier accounting (resident host tokens/
    bytes, spills/readmits/destructive evictions) and the per-tenant
    governor spread; single-tier engines report both as null (the
    pass-through contract)."""
    from mcpx.core.config import MCPXConfig
    from mcpx.engine.engine import InferenceEngine

    eng = InferenceEngine(
        MCPXConfig.from_dict(
            {
                "model": {"size": "test"},
                "engine": {"kv_tier": {"enabled": True, "host_mb": 8.0}},
            }
        )
    )
    st = eng.prefix_cache_stats()
    tier = st["tier"]
    assert tier["enabled"] is True
    for key in (
        "host_tokens", "host_bytes", "host_bytes_budget", "spills",
        "readmits", "destructive_evictions", "denied_readmits",
    ):
        assert key in tier, key
    assert st["governor"] == {}  # no tenants observed yet
    assert "spilled_nodes" in st and "host_pages" in st
    # queue_stats prefix scoreboard extension rides the same counters.
    eng._governor.on_insert("gold", 32)
    assert eng.prefix_cache_stats()["governor"]["gold"]["resident_tokens"] == 32
    off = InferenceEngine(
        MCPXConfig.from_dict({"model": {"size": "test"}})
    )
    st_off = off.prefix_cache_stats()
    assert st_off["tier"] is None and st_off["governor"] is None


def test_missing_registration_returns_400():
    async def go():
        cp, app = make_app()

        async def drive(client):
            r = await client.post("/services", json={"name": "x"})  # no endpoint
            assert r.status == 400

        await with_client(app, drive)

    asyncio.run(go())


def test_profile_transition_in_progress_409(monkeypatch):
    """The concurrency contract of /profile/start|stop (ISSUE 7 satellite):
    while a start's ``start_trace`` is still in flight in a worker thread,
    a concurrent stop must 409 on the _STARTING sentinel ("transition in
    progress") and a concurrent start must 409 on the reservation — neither
    may race jax's single-session profiler state."""
    import threading

    import jax

    release = threading.Event()
    entered = threading.Event()
    calls = {"start": 0, "stop": 0}

    def fake_start(trace_dir):
        calls["start"] += 1
        entered.set()
        release.wait(10)

    def fake_stop():
        calls["stop"] += 1

    async def go():
        cp, app = make_app()
        monkeypatch.setattr(jax.profiler, "start_trace", fake_start)
        monkeypatch.setattr(jax.profiler, "stop_trace", fake_stop)

        async def drive(client):
            task = asyncio.create_task(
                client.post("/profile/start", json={"dir": "/tmp/mcpx-prof-t"})
            )
            assert await asyncio.to_thread(entered.wait, 10)
            # start_trace is blocked in its thread: the reservation is live.
            r = await client.post("/profile/stop")
            assert r.status == 409
            assert "transition in progress" in (await r.json())["error"]
            r2 = await client.post("/profile/start", json={"dir": "/tmp/other"})
            assert r2.status == 409  # reservation counts as "already active"
            release.set()
            r0 = await task
            assert r0.status == 200
            r3 = await client.post("/profile/stop")
            assert r3.status == 200
            assert calls == {"start": 1, "stop": 1}

        await with_client(app, drive)

    asyncio.run(go())


def test_shutdown_during_profiler_transition_skips_flush(monkeypatch):
    """Shutdown racing an in-flight profiler transition must SKIP the
    at-shutdown flush (flushing would race the transition thread inside
    jax's profiler) and clear the sentinel — previously only a code
    comment, now pinned."""
    import threading

    import jax

    release = threading.Event()
    entered = threading.Event()
    calls = {"start": 0, "stop": 0}

    def fake_start(trace_dir):
        calls["start"] += 1

    def fake_stop():
        calls["stop"] += 1
        entered.set()
        release.wait(10)

    async def go():
        cp, app = make_app()
        monkeypatch.setattr(jax.profiler, "start_trace", fake_start)
        monkeypatch.setattr(jax.profiler, "stop_trace", fake_stop)

        async def drive(client):
            r = await client.post("/profile/start", json={"dir": "/tmp/mcpx-prof-s"})
            assert r.status == 200
            task = asyncio.create_task(client.post("/profile/stop"))
            assert await asyncio.to_thread(entered.wait, 10)
            # Stop is mid-flight (_STOPPING). Run the app's cleanup NOW —
            # the shutdown-during-transition path: it must not dispatch a
            # second stop_trace (the flush) and must clear the sentinel.
            before = calls["stop"]
            for cb in app.on_cleanup:
                await cb(app)
            assert calls["stop"] == before  # no flush dispatched
            # Sentinel cleared: the profiler state no longer reads active.
            r2 = await client.post("/profile/stop")
            assert r2.status == 409
            assert "not active" in (await r2.json())["error"]
            release.set()
            r0 = await task
            assert r0.status == 200  # the in-flight stop still completes

        await with_client(app, drive)

    asyncio.run(go())


def test_profile_endpoints(tmp_path):
    """POST /profile/start captures a jax.profiler trace of device work done
    while active; double-start and stop-without-start are 409s."""

    async def go():
        cp, app = make_app()

        async def drive(client):
            trace_dir = str(tmp_path / "traces")
            r = await client.post("/profile/stop")
            assert r.status == 409
            r = await client.post("/profile/start", json={"dir": trace_dir})
            assert r.status == 200, await r.text()
            r2 = await client.post("/profile/start", json={"dir": trace_dir})
            assert r2.status == 409
            # Some device work while the trace is active.
            import jax.numpy as jnp

            jnp.ones((8, 8)).sum().block_until_ready()
            r3 = await client.post("/profile/stop")
            assert r3.status == 200
            assert (await r3.json())["dir"] == trace_dir
            import pathlib

            files = list(pathlib.Path(trace_dir).rglob("*"))
            assert any(f.is_file() for f in files), "no trace artifacts written"

        await with_client(app, drive)

    asyncio.run(go())
