"""Test configuration: force an 8-device virtual CPU mesh before JAX loads.

This is the TPU-world analogue of "test multi-node without a cluster"
(SURVEY.md §4.3): sharding specs, TP decode and collective layouts are
exercised on 8 virtual CPU devices; real-TPU execution is covered by the
driver's bench run.

The arming recipe (env flags + jax config + backend reset when a
sitecustomize already latched the real TPU) lives in one place —
``__graft_entry__._force_virtual_cpu`` — shared with the driver's
multichip dryrun so the two can't drift.
"""

import os

os.environ.setdefault("JAX_ENABLE_X64", "0")

from __graft_entry__ import _force_virtual_cpu  # noqa: E402

_force_virtual_cpu(8)

import jax  # noqa: E402

assert jax.default_backend() == "cpu", "tests must run on CPU"
assert len(jax.devices()) == 8, "tests expect an 8-device virtual CPU mesh"
