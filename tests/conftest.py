"""Test configuration: force an 8-device virtual CPU mesh before JAX loads.

This is the TPU-world analogue of "test multi-node without a cluster"
(SURVEY.md §4.3): sharding specs, TP decode and collective layouts are
exercised on 8 virtual CPU devices; real-TPU execution is covered by the
driver's bench run.
"""

import os

# Force CPU unconditionally: the session env points JAX at a live TPU
# (platform "axon", registered by a sitecustomize that imports jax at
# interpreter start, so env vars alone are latched too late). Unit tests
# must be deterministic, fast, and use full-f32 matmuls (TPU defaults
# matmul inputs to bf16), so override via jax.config after import.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", "tests must run on CPU"
assert len(jax.devices()) == 8, "tests expect an 8-device virtual CPU mesh"
