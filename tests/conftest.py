"""Test configuration: force an 8-device virtual CPU mesh before JAX loads.

This is the TPU-world analogue of "test multi-node without a cluster"
(SURVEY.md §4.3): sharding specs, TP decode and collective layouts are
exercised on 8 virtual CPU devices; real-TPU execution is covered by the
driver's bench run.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
