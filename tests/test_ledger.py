"""Per-request cost ledger & per-tenant usage attribution (ISSUE 14):
bill itemization, bounded tenant fold, the conservation contracts (tenant
roll-ups exactly sum member bills; >= 95% of a traced request's wall
attributed; FLOP apportionment sums to the engine's harvested totals),
and ledger-off pass-through parity on the engine and the server."""

import asyncio
import json
import math
import random

import pytest

from mcpx.core.config import MCPXConfig
from mcpx.telemetry import ledger as ledger_mod
from mcpx.telemetry.ledger import (
    RequestBill,
    UsageLedger,
    count_tool_attempts,
)


def _lcfg(**kw):
    cfg = MCPXConfig.from_dict(
        {"telemetry": {"ledger": {"enabled": True, **kw}}}
    )
    return cfg.telemetry.ledger


# ------------------------------------------------------------------- bill
def test_bill_itemization_finalize_and_to_dict():
    bill = RequestBill(tenant="acme", endpoint="/plan")
    bill.sched_queue_ms += 5.0
    bill.add_engine(
        {
            "engine_queue_ms": 2.0, "prefill_ms": 10.0, "decode_ms": 80.0,
            "prefill_tokens": 30, "prefix_saved_tokens": 16,
            "decode_tokens": 12, "decode_forwards": 12,
            "spec_accepted_tokens": 4, "spill_copy_tokens": 16,
            "kv_page_seconds": 0.5, "flops": 1e9, "hbm_bytes": 2e9,
        }
    )
    # A replanning request generates twice and pays for both.
    bill.add_engine({"decode_ms": 20.0, "decode_tokens": 3, "flops": 1e8})
    bill.note_plan(120.0, 112.0)  # plan wall minus what the engine billed
    bill.add_tools(
        {"nodes": [{"attempts": [
            {"kind": "primary", "status": "error"},
            {"kind": "retry", "status": "ok"},
            {"kind": "hedge", "status": "cancelled"},
        ]}]},
        40.0,
    )
    bill.finalize(status="ok", total_ms=200.0)
    assert bill.generates == 2
    assert bill.decode_tokens == 15
    assert bill.flops == pytest.approx(1.1e9)
    assert bill.tool_attempts == 3
    assert bill.tool_attempts_by_kind == {"primary": 1, "retry": 1, "hedge": 1}
    attributed = 5.0 + 2.0 + 10.0 + (80.0 + 20.0) + 8.0 + 40.0  # = 165
    assert bill.attributed_ms() == pytest.approx(attributed)
    d = bill.to_dict()
    assert d["other_ms"] == pytest.approx(200.0 - attributed, abs=1e-6)
    assert d["attributed_frac"] == pytest.approx(attributed / 200.0, abs=1e-3)
    json.dumps(d)  # bills ride spans/bundles: must stay serializable


def test_count_tool_attempts_survives_malformed_traces():
    assert count_tool_attempts(None) == {}
    assert count_tool_attempts({"nodes": "garbage"}) == {}
    assert count_tool_attempts({"nodes": [{"attempts": [None, 7]}]}) == {}
    assert count_tool_attempts(
        {"nodes": [{"attempts": [{"kind": "fallback"}]}, "junk"]}
    ) == {"fallback": 1}


def test_contextvar_activate_deactivate():
    assert ledger_mod.current_bill() is None
    bill = RequestBill()
    token = ledger_mod.activate(bill)
    assert ledger_mod.current_bill() is bill
    ledger_mod.deactivate(token)
    assert ledger_mod.current_bill() is None


# ---------------------------------------------------------------- usage fold
def test_usage_ledger_folds_tenant_cardinality():
    led = UsageLedger(_lcfg(max_tenants=2))
    for i, tenant in enumerate(["a", "b", "c", "d", "a"]):
        bill = RequestBill(tenant=tenant)
        bill.add_engine({"decode_tokens": i})
        bill.finalize(status="ok", total_ms=1.0)
        led.observe(bill)
    snap = led.snapshot()
    assert set(snap["tenants"]) == {"a", "b", "other"}
    assert snap["tenants"]["other"]["requests"] == 2  # c + d folded
    assert snap["totals"]["requests"] == 5


def test_tenant_rollups_exactly_sum_member_bills():
    """Conservation (ISSUE 14 acceptance): per-tenant ledger totals equal
    the sum of member request bills — property-tested over seeded
    mixed-tenant traffic, exact float equality (same fold, same order)."""
    rng = random.Random(1234)
    led = UsageLedger(_lcfg(max_tenants=8, recent=512))
    tenants = ["t0", "t1", "t2", "t3", "t4"]
    bills: list[RequestBill] = []
    for _ in range(300):
        bill = RequestBill(
            tenant=rng.choice(tenants), endpoint="/plan",
            degraded=rng.random() < 0.2,
        )
        bill.sched_queue_ms += rng.uniform(0, 5)
        for _g in range(rng.randint(1, 3)):
            bill.add_engine(
                {
                    "engine_queue_ms": rng.uniform(0, 2),
                    "prefill_ms": rng.uniform(0, 20),
                    "decode_ms": rng.uniform(0, 200),
                    "prefill_tokens": rng.randint(0, 64),
                    "prefix_saved_tokens": rng.randint(0, 32),
                    "decode_tokens": rng.randint(1, 48),
                    "decode_forwards": rng.randint(1, 48),
                    "flops": rng.uniform(0, 1e9),
                    "hbm_bytes": rng.uniform(0, 1e9),
                    "kv_page_seconds": rng.uniform(0, 3),
                }
            )
        bill.note_plan(rng.uniform(0, 50), rng.uniform(0, 10))
        bill.finalize(status="ok", total_ms=rng.uniform(1, 400))
        led.observe(bill)
        bills.append(bill)
    snap = led.snapshot()
    assert len(snap["recent"]) == 300  # ring big enough: every bill audited
    for tenant in set(b.tenant for b in bills):
        member = [b for b in bills if b.tenant == tenant]
        acct = led.tenant_totals(tenant)
        assert acct["requests"] == len(member)
        for key in ("decode_tokens", "prefill_tokens", "decode_forwards"):
            assert acct[key] == sum(getattr(b, key) for b in member), (
                tenant, key,
            )
        # Float items: the ledger folds += in completion order, the exact
        # order this sum replays — raw equality is EXACT, bit for bit.
        for key in ("flops", "hbm_bytes", "decode_ms", "kv_page_seconds"):
            assert acct[key] == sum(getattr(b, key) for b in member), (
                tenant, key,
            )
    # Grand totals equal the tenant sums.
    for key in ("requests", "decode_tokens"):
        assert snap["totals"][key] == sum(
            a[key] for a in snap["tenants"].values()
        )


# ------------------------------------------------------------- engine side
def _engine_cfg(ledger_on: bool, **engine_overrides):
    return MCPXConfig.from_dict(
        {
            "model": {"size": "test", "max_seq_len": 256},
            "engine": {
                "use_pallas": False,
                "max_batch_size": 4,
                "max_decode_len": 24,
                "kv_page_size": 16,
                "max_pages_per_seq": 16,
                "temperature": 0.0,
                **engine_overrides,
            },
            "telemetry": {"ledger": {"enabled": ledger_on}},
        }
    )


def test_engine_bills_conserve_flops_and_off_is_pass_through():
    """Engine acceptance: concurrent mixed-tenant generates produce bills
    whose FLOPs/HBM bytes sum EXACTLY to the engine's apportioned totals
    (which mirror the cost observatory's harvested per-call costs, split
    per executable); with the ledger off, outputs are byte-identical,
    GenerateResult.bill is None, and queue_stats is untouched."""
    from mcpx.engine.engine import InferenceEngine

    async def run(ledger_on: bool):
        eng = InferenceEngine(_engine_cfg(ledger_on))
        await eng.start()
        try:
            prompts = [
                eng.tokenizer.encode(f"plan request number {i}")
                for i in range(6)
            ]
            results = await asyncio.gather(
                *(
                    eng.generate(
                        p, max_new_tokens=16, constrained=False,
                        tenant=f"t{i % 3}",
                    )
                    for i, p in enumerate(prompts)
                )
            )
            return results, eng.ledger_totals(), dict(eng.queue_stats())
        finally:
            await eng.aclose()

    async def go():
        res_on, totals_on, qs_on = await run(True)
        res_off, totals_off, qs_off = await run(False)
        # Pass-through parity: byte-identical tokens, same queue_stats
        # surface, no bill, nothing apportioned.
        assert [r.token_ids for r in res_on] == [r.token_ids for r in res_off]
        assert all(r.bill is None for r in res_off)
        assert totals_off == {"flops": 0.0, "bytes": 0.0, "by_executable": {}}
        assert qs_on.keys() == qs_off.keys()
        # Every billed request carries the itemized engine bill.
        bills = [r.bill for r in res_on]
        assert all(b is not None for b in bills)
        for r, b in zip(res_on, bills):
            assert b["decode_tokens"] == r.generated_tokens
            assert b["prefill_tokens"] > 0
            assert b["decode_forwards"] > 0
            assert b["kv_pages"] > 0 and b["kv_page_seconds"] > 0
            assert b["engine_queue_ms"] == pytest.approx(r.queue_ms)
            assert b["decode_ms"] == pytest.approx(r.decode_ms)
        # FLOP/HBM conservation: sum of bills == the apportioned totals ==
        # the per-executable split (within float rounding).
        assert totals_on["flops"] > 0
        assert math.isclose(
            sum(b["flops"] for b in bills), totals_on["flops"],
            rel_tol=1e-9, abs_tol=1.0,
        )
        assert math.isclose(
            sum(b["hbm_bytes"] for b in bills), totals_on["bytes"],
            rel_tol=1e-9, abs_tol=1.0,
        )
        assert math.isclose(
            sum(totals_on["by_executable"].values()), totals_on["flops"],
            rel_tol=1e-9, abs_tol=1.0,
        )
        # The decode/prefill executables both contributed.
        assert any("prefill" in k for k in totals_on["by_executable"])
        assert any("segment" in k for k in totals_on["by_executable"])

    asyncio.run(go())


def test_engine_prefix_reuse_bills_saved_tokens():
    """A second request sharing a prompt head bills prefix_saved_tokens
    (tokens served from radix KV) and a smaller suffix prefill."""
    from mcpx.engine.engine import InferenceEngine

    async def go():
        eng = InferenceEngine(_engine_cfg(True))
        await eng.start()
        try:
            base = eng.tokenizer.encode(
                "shared planner header with a long common prompt prefix. "
            )
            a = await eng.generate(
                base + eng.tokenizer.encode("first suffix"),
                max_new_tokens=8, constrained=False,
            )
            b = await eng.generate(
                base + eng.tokenizer.encode("second suffix"),
                max_new_tokens=8, constrained=False,
            )
            assert a.bill["prefix_saved_tokens"] == 0
            assert b.bill["prefix_saved_tokens"] > 0
            assert b.bill["prefill_tokens"] < a.bill["prefill_tokens"]
        finally:
            await eng.aclose()

    asyncio.run(go())


# ---------------------------------------------------------- full-stack e2e
def test_traced_request_wall_conservation_full_stack():
    """ISSUE 14 acceptance: for a traced /plan through the real stack
    (LLM planner, engine, middleware), the bill's wall-time parts sum to
    >= 95% of the root span's wall, the bill rides the root span, and the
    tenant roll-up at GET /usage matches the recent bills."""
    from aiohttp.test_utils import TestClient, TestServer

    from mcpx.engine.engine import InferenceEngine
    from mcpx.planner.llm import LLMPlanner
    from mcpx.registry.base import ServiceRecord
    from mcpx.server.app import build_app
    from mcpx.server.factory import build_control_plane

    cfg = MCPXConfig.from_dict(
        {
            "model": {"size": "test", "max_seq_len": 256},
            "engine": {
                "use_pallas": False,
                "max_batch_size": 4,
                "max_decode_len": 48,
                "max_pages_per_seq": 16,
                "temperature": 0.0,
            },
            "planner": {"kind": "llm", "plan_cache_size": 0},
            "telemetry": {"ledger": {"enabled": True}},
        }
    )
    eng = InferenceEngine(cfg)
    cp = build_control_plane(cfg, planner=LLMPlanner(eng, cfg.planner))
    app = build_app(cp)

    async def go():
        for i in range(3):
            await cp.registry.put(
                ServiceRecord(
                    name=f"svc{i}",
                    endpoint=f"local://svc{i}",
                    description=f"fetch and summarize topic {i} data",
                    input_schema={"q": "str"},
                    output_schema={"data": "str"},
                )
            )
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            # Warm once (grammar build, first-compile tails), then measure.
            r = await client.post(
                "/plan", json={"intent": "fetch data warmup"}
            )
            assert r.status == 200, await r.text()
            r = await client.post(
                "/plan",
                json={"intent": "fetch and summarize topic data"},
                headers={"X-MCPX-Tenant": "acme"},
            )
            assert r.status == 200, await r.text()
            trace_id = r.headers["X-Trace-Id"]
            rec = cp.tracer.get(trace_id)
            assert rec is not None
            root = rec.spans[0]
            bill = root.attrs.get("bill")
            assert bill is not None, "bill missing from root span attrs"
            assert bill["tenant"] == "acme"
            assert bill["decode_tokens"] > 0
            # Conservation: >= 95% of the root span's wall is itemized.
            parts = (
                bill["sched_queue_ms"] + bill["engine_queue_ms"]
                + bill["prefill_ms"] + bill["decode_ms"]
                + bill["plan_other_ms"] + bill["tool_ms"]
            )
            assert bill["total_ms"] == pytest.approx(rec.total_ms, rel=0.05)
            assert parts >= 0.95 * rec.total_ms, (
                f"attributed {parts:.1f}ms of {rec.total_ms:.1f}ms "
                f"({parts / rec.total_ms:.2%}): {bill}"
            )
            # The tenant roll-up equals the member bills at GET /usage.
            usage = await (await client.get("/usage")).json()
            acme = usage["tenants"]["acme"]
            member = [
                b for b in usage["recent"] if b["tenant"] == "acme"
            ]
            assert acme["requests"] == len(member) == 1
            assert acme["decode_tokens"] == sum(
                b["decode_tokens"] for b in member
            )
            assert acme["flops"] == pytest.approx(
                sum(b["flops"] for b in member), rel=1e-9
            )
        finally:
            await client.close()

    asyncio.run(go())


def test_server_ledger_off_is_pass_through():
    """Default config: cp.ledger is None, /usage answers enabled:false,
    responses carry no billing artifacts."""
    from aiohttp.test_utils import TestClient, TestServer

    from mcpx.server.app import build_app
    from mcpx.server.factory import build_control_plane

    cp = build_control_plane(MCPXConfig())
    assert cp.ledger is None and cp.slo is None
    app = build_app(cp)

    async def go():
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            resp = await client.get("/usage")
            assert resp.status == 200
            assert await resp.json() == {"enabled": False}
            resp = await client.get("/slo")
            assert resp.status == 200
            assert await resp.json() == {"enabled": False}
        finally:
            await client.close()

    asyncio.run(go())
