"""Model correctness on CPU: prefill/decode agreement, padding invariance,
sampling, tokenizer round-trips (SURVEY.md §4.1/§4.5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mcpx.engine.sampling import sample
from mcpx.models.gemma import (
    GemmaConfig,
    decode_step,
    init_kv_cache,
    init_params,
    prefill,
)
from mcpx.models.tokenizer import ByteTokenizer


@pytest.fixture(scope="module")
def cfg():
    # float32 for tight numeric comparisons on CPU.
    return GemmaConfig(dtype="float32", max_seq_len=64)


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(cfg, jax.random.PRNGKey(0))


def test_tokenizer_roundtrip():
    tok = ByteTokenizer()
    text = 'plan: {"nodes": [1, 2]} — ünïcode ✓'
    ids = tok.encode(text, bos=True, eos=True)
    assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
    assert tok.decode(ids) == text
    assert tok.vocab_size % 128 == 0


def test_prefill_shapes(cfg, params):
    B, T, S = 2, 8, 16
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, 256)
    cache = init_kv_cache(cfg, B, S)
    logits, cache = prefill(params, cfg, tokens, jnp.array([T, T]), cache)
    assert logits.shape == (B, T, cfg.vocab_size)
    assert cache["k"].shape == (cfg.n_layers, B, S, cfg.n_kv_heads, cfg.head_dim)
    assert not np.any(np.isnan(logits))


def test_decode_matches_prefill(cfg, params):
    """Token-by-token decode must reproduce full-sequence prefill logits."""
    B, T, S = 1, 10, 16
    tokens = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, 256)

    cache = init_kv_cache(cfg, B, S)
    full_logits, _ = prefill(params, cfg, tokens, jnp.array([T]), cache)

    # Prefill just the first token, then decode the rest one at a time.
    cache = init_kv_cache(cfg, B, S)
    step_logits, cache = prefill(params, cfg, tokens[:, :1], jnp.array([1]), cache)
    got = [step_logits[:, 0]]
    for t in range(1, T):
        lg, cache = decode_step(params, cfg, tokens[:, t], jnp.array([t]), cache)
        got.append(lg)
    got = jnp.stack(got, axis=1)  # [B, T, V]
    np.testing.assert_allclose(np.asarray(got), np.asarray(full_logits), rtol=2e-4, atol=2e-4)


def test_padding_invariance(cfg, params):
    """Right-padding beyond seq_len must not change valid-position logits."""
    B, T = 1, 6
    tok = ByteTokenizer()
    tokens = jax.random.randint(jax.random.PRNGKey(3), (B, T), 0, 256)
    padded = jnp.concatenate(
        [tokens, jnp.full((B, 4), tok.pad_id, tokens.dtype)], axis=1
    )
    cache_a = init_kv_cache(cfg, B, 16)
    cache_b = init_kv_cache(cfg, B, 16)
    la, _ = prefill(params, cfg, tokens, jnp.array([T]), cache_a)
    lb, _ = prefill(params, cfg, padded, jnp.array([T]), cache_b)
    np.testing.assert_allclose(
        np.asarray(la), np.asarray(lb[:, :T]), rtol=1e-5, atol=1e-5
    )


def test_batch_order_invariance(cfg, params):
    """Each batch row is independent (mask correctness across rows)."""
    T = 5
    t1 = jax.random.randint(jax.random.PRNGKey(4), (1, T), 0, 256)
    t2 = jax.random.randint(jax.random.PRNGKey(5), (1, T), 0, 256)
    both = jnp.concatenate([t1, t2], axis=0)
    la, _ = prefill(params, cfg, both, jnp.array([T, T]), init_kv_cache(cfg, 2, 8))
    lb, _ = prefill(params, cfg, t1, jnp.array([T]), init_kv_cache(cfg, 1, 8))
    np.testing.assert_allclose(np.asarray(la[0]), np.asarray(lb[0]), rtol=1e-5, atol=1e-5)


def test_sampling_modes():
    key = jax.random.PRNGKey(0)
    logits = jnp.array([[0.0, 5.0, 1.0, -2.0]])
    # Greedy.
    assert int(sample(logits, key)[0]) == 1
    # Mask blocks the argmax.
    mask = jnp.array([[True, False, True, True]])
    assert int(sample(logits, key, mask=mask)[0]) == 2
    # Temperature sampling stays within the mask.
    for i in range(5):
        t = sample(logits, jax.random.PRNGKey(i), temperature=1.0, top_k=2, mask=mask)
        assert int(t[0]) in (0, 2, 3)


def test_named_configs():
    c2b = GemmaConfig.named("2b")
    assert c2b.n_layers == 18 and c2b.n_kv_heads == 1
    c7b = GemmaConfig.named("7b")
    assert c7b.n_heads == c7b.n_kv_heads == 16
    with pytest.raises(Exception):
        GemmaConfig.named("70b")
