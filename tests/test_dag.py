"""Unit tests for the canonical DAG IR (SURVEY.md §4.1)."""

import pytest

from mcpx.core.dag import DagEdge, DagNode, Plan, PlanValidationError, linear_plan


def test_linear_plan_generations():
    p = linear_plan(["a", "b", "c"])
    assert p.topological_generations() == [["a"], ["b"], ["c"]]


def test_fan_out_fan_in_generations():
    p = Plan(
        nodes=[DagNode(name=n) for n in ["src", "l", "r", "sink"]],
        edges=[
            DagEdge("src", "l"),
            DagEdge("src", "r"),
            DagEdge("l", "sink"),
            DagEdge("r", "sink"),
        ],
    )
    p.validate()
    assert p.topological_generations() == [["src"], ["l", "r"], ["sink"]]


def test_cycle_detected():
    p = Plan(
        nodes=[DagNode(name=n) for n in ["a", "b"]],
        edges=[DagEdge("a", "b"), DagEdge("b", "a")],
    )
    with pytest.raises(PlanValidationError, match="cycle"):
        p.validate()


def test_duplicate_node_names_rejected():
    p = Plan(nodes=[DagNode(name="a"), DagNode(name="a")])
    with pytest.raises(PlanValidationError, match="duplicate"):
        p.validate()


def test_dangling_edge_rejected():
    p = Plan(nodes=[DagNode(name="a")], edges=[DagEdge("a", "ghost")])
    with pytest.raises(PlanValidationError, match="unknown node 'ghost'"):
        p.validate()


def test_self_loop_rejected():
    p = Plan(nodes=[DagNode(name="a")], edges=[DagEdge("a", "a")])
    with pytest.raises(PlanValidationError, match="self-loop"):
        p.validate()


def test_reference_wire_format_roundtrip():
    # The orchestrator envelope of the reference (control_plane.py:96-100).
    wire = {
        "nodes": [
            {"name": "fetch", "endpoint": "http://svc/fetch", "inputs": {"q": "query"}},
            {"name": "summarize", "endpoint": "http://svc/sum", "inputs": {"text": "fetch"}},
        ],
        "edges": [{"from": "fetch", "to": "summarize", "fallback": "http://backup/sum"}],
    }
    p = Plan.from_wire(wire)
    assert [n.name for n in p.nodes] == ["fetch", "summarize"]
    # Edge-level fallback (reference shape) folds into the dst node's ordered chain.
    assert p.node("summarize").fallbacks == ["http://backup/sum"]
    out = p.to_wire()
    assert out["nodes"][0]["name"] == "fetch"
    assert out["edges"][0]["from"] == "fetch"


def test_planner_steps_shape_normalised():
    # The step-list shape the reference prompt requests (control_plane.py:61-62).
    wire = {
        "steps": [
            {"service_name": "a", "input_keys": ["query"], "next_steps": ["b"]},
            {"service_name": "b", "input_keys": {"text": "a"}, "fallback": "http://fb/b"},
        ]
    }
    p = Plan.from_wire(wire)
    assert p.topological_generations() == [["a"], ["b"]]
    assert p.node("a").inputs == {"query": "query"}
    assert p.node("b").inputs == {"text": "a"}
    assert p.node("b").fallbacks == ["http://fb/b"]


def test_from_json_invalid_json():
    with pytest.raises(PlanValidationError, match="invalid JSON"):
        Plan.from_json("not json {")


def test_bad_inputs_type_listed_in_problems():
    with pytest.raises(PlanValidationError) as ei:
        Plan.from_wire({"nodes": [{"name": "a", "inputs": {"x": 3}}], "edges": []})
    assert any("inputs" in p for p in ei.value.problems)


def test_predecessors():
    p = linear_plan(["a", "b", "c"])
    assert p.predecessors("c") == ["b"]
    assert p.predecessors("a") == []
