"""Long-prompt routing through sequence-parallel ring prefill (VERDICT r3
next #8): the served path, not just the demo kernel — a long prompt admits
through ``InferenceEngine._prefill_impl(ring=True)`` (``ring_prefill`` on
the seq-viewed mesh) and produces the same greedy plan as the dense path."""

import asyncio

from mcpx.core.config import MCPXConfig
from mcpx.engine.engine import InferenceEngine
from mcpx.models.gemma.config import GemmaConfig
from mcpx.parallel.mesh import make_mesh

# float32 end to end so dense-vs-ring softmax accumulation cannot wobble
# the greedy argmax (same rationale as the multichip equality test).
MODEL_F32 = GemmaConfig(
    vocab_size=384,
    d_model=128,
    n_layers=2,
    n_heads=4,
    n_kv_heads=4,
    head_dim=32,
    d_ff=256,
    dtype="float32",
    max_seq_len=512,
)


def _cfg(ring_min: int):
    return MCPXConfig.from_dict(
        {
            "model": {"size": "test", "max_seq_len": 512},
            "engine": {
                "use_pallas": False,
                "max_batch_size": 2,
                "max_decode_len": 48,
                "kv_page_size": 16,
                "max_pages_per_seq": 32,
                "temperature": 0.0,
                "ring_prefill_min_tokens": ring_min,
            },
        }
    )


def test_long_prompt_routes_through_ring_and_matches_dense():
    # ~300-byte prompt -> 512-token prefill bucket, over the 256 threshold;
    # short prompt stays under it and must take the dense path.
    long_prompt = (
        "Compose a service DAG over the following services. "
        + " ".join(f"svc-{i:03d} in:query out:result" for i in range(18))
        + " Intent: fetch then summarize. JSON:"
    )
    short_prompt = "plan. JSON:"

    async def run_one(ring_min: int):
        mesh = make_mesh(data=4, model=2)
        eng = InferenceEngine(_cfg(ring_min), model_cfg=MODEL_F32, mesh=mesh)
        await eng.start()
        try:
            if ring_min:
                # Routing is armed: seq mesh spans the 4 data devices.
                assert eng._seq_mesh is not None
                assert eng._seq_mesh.shape["seq"] == 4
            else:
                assert eng._seq_mesh is None
            out_long = await eng.generate(
                eng.tokenizer.encode(long_prompt), max_new_tokens=40
            )
            out_short = await eng.generate(
                eng.tokenizer.encode(short_prompt), max_new_tokens=24
            )
            rings = eng.metrics.ring_prefills._value.get()
            return out_long.token_ids, out_short.token_ids, rings
        finally:
            await eng.aclose()

    async def go():
        ring_long, ring_short, n_ring = await run_one(ring_min=256)
        dense_long, dense_short, n_dense = await run_one(ring_min=0)
        # The long prompt (and only it) went through ring prefill...
        assert n_ring == 1, n_ring
        assert n_dense == 0
        # ...and the serving output is identical to the dense path.
        assert ring_long == dense_long
        assert ring_short == dense_short

    asyncio.run(go())


def test_injected_seq_mesh_is_reused():
    """An engine constructed on a mesh that already carries a real seq axis
    rings over THAT mesh — no reshape, no silent disable."""

    async def go():
        mesh = make_mesh(data=1, seq=4, model=2)
        eng = InferenceEngine(_cfg(ring_min=256), model_cfg=MODEL_F32, mesh=mesh)
        await eng.start()
        try:
            assert eng._seq_mesh is mesh
            assert eng._ring_ok(256) and not eng._ring_ok(64)
        finally:
            await eng.aclose()

    asyncio.run(go())
