import asyncio

import pytest

from mcpx.core.config import PlannerConfig
from mcpx.core.errors import PlannerError
from mcpx.planner import HeuristicPlanner, MockPlanner, PlanContext
from mcpx.registry import InMemoryRegistry, ServiceRecord
from mcpx.telemetry.stats import TelemetryStore


def run(coro):
    return asyncio.run(coro)


async def registry_with(*records):
    reg = InMemoryRegistry()
    for r in records:
        await reg.put(r)
    return reg


def svc(name, ins, outs, desc="", **kw):
    return ServiceRecord(
        name=name,
        endpoint=f"local://{name}",
        description=desc or name,
        input_schema={k: "str" for k in ins},
        output_schema={k: "str" for k in outs},
        **kw,
    )


def test_mock_planner_canned_and_unknown():
    from mcpx.core.dag import linear_plan

    p = linear_plan(["a"])

    async def go():
        reg = await registry_with()
        mp = MockPlanner(by_intent={"known": p})
        ctx = PlanContext(registry=reg)
        got = await mp.plan("known", ctx)
        assert [n.name for n in got.nodes] == ["a"]
        with pytest.raises(PlannerError):
            await mp.plan("unknown", ctx)

    run(go())


def test_heuristic_chains_by_schema():
    async def go():
        reg = await registry_with(
            svc("search", ["query"], ["document"], "search the web for documents"),
            svc("summarize", ["document"], ["summary"], "summarize a document"),
            svc("unrelated", ["zzz"], ["qqq"], "completely different billing thing"),
        )
        planner = HeuristicPlanner(PlannerConfig(shortlist_top_k=2))
        plan = await planner.plan("search for a document and summarize it", PlanContext(registry=reg))
        names = [n.name for n in plan.nodes]
        assert "search" in names and "summarize" in names
        assert "unrelated" not in names
        # summarize consumes search's document output.
        assert plan.node("summarize").inputs["document"] == "search"
        assert plan.topological_generations() == [["search"], ["summarize"]]
        assert plan.explanation  # README.md:50 made real
        # Endpoints resolved from the registry, not invented.
        assert plan.node("search").endpoint == "local://search"

    run(go())


def test_heuristic_penalises_failing_service():
    async def go():
        reg = await registry_with(
            svc("rank-a", ["query"], ["score"], "rank results by query score"),
            svc("rank-b", ["query"], ["score"], "rank results by query score"),
        )
        ts = TelemetryStore(alpha=0.5)
        for _ in range(10):
            ts.record("rank-a", latency_ms=10, ok=False)
            ts.record("rank-b", latency_ms=10, ok=True)
        planner = HeuristicPlanner(PlannerConfig(shortlist_top_k=1))
        plan = await planner.plan(
            "rank results by query score",
            PlanContext(registry=reg, telemetry=ts.snapshot()),
        )
        assert [n.name for n in plan.nodes] == ["rank-b"]

    run(go())


def test_heuristic_respects_exclude_and_shortlist():
    async def go():
        reg = await registry_with(
            svc("a", ["query"], ["x"], "query handler alpha"),
            svc("b", ["query"], ["x"], "query handler beta"),
        )
        planner = HeuristicPlanner(PlannerConfig(shortlist_top_k=1))
        plan = await planner.plan(
            "query handler", PlanContext(registry=reg, exclude={"a"})
        )
        assert [n.name for n in plan.nodes] == ["b"]
        plan = await planner.plan(
            "query handler", PlanContext(registry=reg, shortlist=["a"])
        )
        assert [n.name for n in plan.nodes] == ["a"]

    run(go())


def test_heuristic_empty_registry_raises():
    async def go():
        reg = await registry_with()
        with pytest.raises(PlannerError, match="empty"):
            await HeuristicPlanner().plan("anything", PlanContext(registry=reg))

    run(go())
