from mcpx.telemetry.metrics import Metrics
from mcpx.telemetry.stats import TelemetryStore


def test_ewma_converges():
    t = TelemetryStore(alpha=0.5)
    for _ in range(20):
        t.record("svc", latency_ms=100.0, ok=True)
    s = t.get("svc")
    assert abs(s.ewma_latency_ms - 100.0) < 1e-6
    assert s.ewma_error_rate == 0.0
    assert s.calls == 20


def test_error_rate_tracks_failures():
    t = TelemetryStore(alpha=0.5)
    t.record("svc", latency_ms=10, ok=True)
    for _ in range(10):
        t.record("svc", latency_ms=10, ok=False)
    s = t.get("svc")
    assert s.ewma_error_rate > 0.9
    assert s.errors == 10


def test_metrics_render_isolated_registries():
    m1, m2 = Metrics(), Metrics()
    m1.plans.labels(planner="Mock", origin="mock", status="ok").inc()
    text = m1.render().decode()
    assert "mcpx_plans_total" in text
    assert 'planner="Mock"' in text
    # Second instance has its own registry: no cross-talk.
    assert 'planner="Mock"' not in m2.render().decode()
