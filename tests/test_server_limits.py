"""Admission control (429), request timeout (504), trace-ID propagation."""

import asyncio

from mcpx.core.config import MCPXConfig
from mcpx.core.dag import linear_plan
from mcpx.orchestrator.transport import RouterTransport
from mcpx.planner.mock import MockPlanner
from mcpx.server.app import build_app
from mcpx.server.factory import build_control_plane

from tests.helpers import FakeService, make_transport
from tests.test_server import with_client


def test_max_concurrency_429_and_trace_header():
    slow = FakeService("slow", result={"v": 1})

    async def go():
        cfg = MCPXConfig.from_dict({"server": {"max_concurrency": 1}})
        transport = RouterTransport(local=make_transport(slow, latencies={"slow": 0.3}))
        plan = linear_plan(["slow"])
        plan.nodes[0].endpoint = "local://slow"
        cp = build_control_plane(cfg, transport=transport, planner=MockPlanner(plan=plan))

        async def drive(client):
            graph = {"nodes": [{"name": "slow", "endpoint": "local://slow"}], "edges": []}
            r1, r2 = await asyncio.gather(
                client.post("/execute", json={"graph": graph}),
                client.post("/execute", json={"graph": graph}),
            )
            statuses = sorted([r1.status, r2.status])
            assert statuses == [200, 429], statuses
            ok = r1 if r1.status == 200 else r2
            assert ok.headers.get("X-Trace-Id")
            # Non-limited endpoints stay available while saturated.
            r = await client.get("/healthz")
            assert r.status == 200

        await with_client(build_app(cp), drive)

    asyncio.run(go())


def test_request_timeout_504():
    slow = FakeService("slow", result={"v": 1})

    async def go():
        cfg = MCPXConfig.from_dict({"server": {"request_timeout_s": 0.05}})
        transport = RouterTransport(local=make_transport(slow, latencies={"slow": 0.5}))
        cp = build_control_plane(cfg, transport=transport)

        async def drive(client):
            graph = {
                "nodes": [{"name": "slow", "endpoint": "local://slow", "timeout_s": 2.0}],
                "edges": [],
            }
            r = await client.post("/execute", json={"graph": graph})
            assert r.status == 504
            body = await r.json()
            assert "exceeded" in body["error"]

        await with_client(build_app(cp), drive)

    asyncio.run(go())


def test_mock_planner_no_aliasing():
    async def go():
        plan = linear_plan(["a"])
        mp = MockPlanner(plan=plan)
        from mcpx.planner.base import PlanContext
        from mcpx.registry import InMemoryRegistry

        ctx = PlanContext(registry=InMemoryRegistry())
        p1 = await mp.plan("intent-1", ctx)
        p2 = await mp.plan("intent-2", ctx)
        assert p1 is not p2 and p1 is not plan
        assert p1.intent == "intent-1" and p2.intent == "intent-2"
        assert plan.intent == ""  # template untouched

    asyncio.run(go())
