"""Admission control (429), request timeout (504), trace-ID propagation."""

import asyncio

from mcpx.core.config import MCPXConfig
from mcpx.core.dag import linear_plan
from mcpx.orchestrator.transport import RouterTransport
from mcpx.planner.mock import MockPlanner
from mcpx.server.app import build_app
from mcpx.server.factory import build_control_plane

from tests.helpers import FakeService, make_transport
from tests.test_server import with_client


def test_max_concurrency_429_and_trace_header():
    slow = FakeService("slow", result={"v": 1})

    async def go():
        cfg = MCPXConfig.from_dict({"server": {"max_concurrency": 1}})
        transport = RouterTransport(local=make_transport(slow, latencies={"slow": 0.3}))
        plan = linear_plan(["slow"])
        plan.nodes[0].endpoint = "local://slow"
        cp = build_control_plane(cfg, transport=transport, planner=MockPlanner(plan=plan))

        async def drive(client):
            graph = {"nodes": [{"name": "slow", "endpoint": "local://slow"}], "edges": []}
            r1, r2 = await asyncio.gather(
                client.post("/execute", json={"graph": graph}),
                client.post("/execute", json={"graph": graph}),
            )
            statuses = sorted([r1.status, r2.status])
            assert statuses == [200, 429], statuses
            ok = r1 if r1.status == 200 else r2
            assert ok.headers.get("X-Trace-Id")
            # Non-limited endpoints stay available while saturated.
            r = await client.get("/healthz")
            assert r.status == 200

        await with_client(build_app(cp), drive)

    asyncio.run(go())


def test_request_timeout_504():
    slow = FakeService("slow", result={"v": 1})

    async def go():
        cfg = MCPXConfig.from_dict({"server": {"request_timeout_s": 0.05}})
        transport = RouterTransport(local=make_transport(slow, latencies={"slow": 0.5}))
        cp = build_control_plane(cfg, transport=transport)

        async def drive(client):
            graph = {
                "nodes": [{"name": "slow", "endpoint": "local://slow", "timeout_s": 2.0}],
                "edges": [],
            }
            r = await client.post("/execute", json={"graph": graph})
            assert r.status == 504
            body = await r.json()
            assert "exceeded" in body["error"]

        await with_client(build_app(cp), drive)

    asyncio.run(go())


def test_mock_planner_no_aliasing():
    async def go():
        plan = linear_plan(["a"])
        mp = MockPlanner(plan=plan)
        from mcpx.planner.base import PlanContext
        from mcpx.registry import InMemoryRegistry

        ctx = PlanContext(registry=InMemoryRegistry())
        p1 = await mp.plan("intent-1", ctx)
        p2 = await mp.plan("intent-2", ctx)
        assert p1 is not p2 and p1 is not plan
        assert p1.intent == "intent-1" and p2.intent == "intent-2"
        assert plan.intent == ""  # template untouched

    asyncio.run(go())


def test_plan_timeout_reaps_engine_row_and_capacity_recovers():
    """The server's request timeout (504) must also FREE the engine row the
    abandoned /plan occupied — the wait_for cancellation propagates into the
    engine future and the worker reaps the row — so a later request gets
    the capacity instead of queueing behind a zombie decode."""

    async def go():
        cfg = MCPXConfig.from_dict(
            {
                "model": {"size": "test", "max_seq_len": 256},
                "server": {"request_timeout_s": 0.4},
                "planner": {"kind": "llm", "max_plan_retries": 0},
                "retrieval": {"enabled": False},
                "engine": {
                    "use_pallas": False,
                    "max_batch_size": 1,  # a single row: a zombie would block ALL capacity
                    "max_decode_len": 96,
                    "kv_page_size": 16,
                    "max_pages_per_seq": 16,
                    "temperature": 0.0,
                    "decode_steps_per_tick": 1,
                    "speculate_k": 0,
                },
            }
        )
        from mcpx.registry.base import ServiceRecord

        cp = build_control_plane(cfg)
        await cp.registry.put(ServiceRecord(name="svc-a", endpoint="local://svc-a"))
        await cp.startup()
        eng = cp.planner.engine

        async def drive(client):
            r = await client.post("/plan", json={"intent": "slow plan please"})
            assert r.status == 504  # byte-vocab 96-token decode outlasts 0.4s on CPU
            # The engine reaps the abandoned row at a tick boundary. The
            # planner's shared-prefix KV entry legitimately stays resident
            # (refs 0, evictable) — only ROW sequences must drain.
            def row_seqs():
                return eng._allocator.stats().sequences - len(eng._prefix_cache)

            for _ in range(1200):
                await asyncio.sleep(0.05)
                if row_seqs() == 0 and eng._slab.n_active == 0:
                    break
            # The capacity property, not the mechanism: depending on where
            # the cancellation lands the row is reaped mid-decode, skipped
            # at admission, or retired — in every case the single slab row
            # must come back and the engine must still serve.
            assert row_seqs() == 0 and eng._slab.n_active == 0
            res = await eng.generate(
                eng.tokenizer.encode("quick"), max_new_tokens=4
            )
            assert res.generated_tokens > 0

        await with_client(build_app(cp), drive)
        await eng.aclose()

    asyncio.run(go())
