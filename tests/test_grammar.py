"""Grammar DFA tests: acceptance, rejection, mask/transition table
consistency, and device-side constrained sampling (SURVEY.md §4.1)."""

import numpy as np

from mcpx.core.dag import Plan
from mcpx.models.tokenizer import ByteTokenizer
from mcpx.planner.grammar import build_plan_grammar


def test_accepts_valid_plans():
    g = build_plan_grammar()
    for text in [
        '{"steps":[{"s":"search","in":["query"],"next":["sum"]},{"s":"sum","in":[],"next":[]}]}',
        '{"steps":[{"s":"a","in":[],"next":[]}]}',
        '{"steps":[{"s":"a","in":["x","y"],"next":[]},{"s":"b","in":[],"next":[]}]}',
    ]:
        final = g.walk(text)
        assert g.is_accept(final), text
        # And what the grammar accepts, the Plan parser accepts.
        plan = Plan.from_json(text)
        assert plan.nodes


def test_rejects_invalid():
    g = build_plan_grammar()
    for text in [
        '{"steps":[]}',  # empty steps not allowed
        '{"steps":[{"s":"a"}]}',  # missing keys
        '{"nodes":[]}',  # wrong envelope
        '{"steps":[{"s":"a","in":[],"next":[]}]',  # unterminated
        'plain text',
        '{"steps":[{"s":"a\\"","in":[],"next":[]}]}',  # escape rejected
        '{"steps":[{"s":"","in":[],"next":[]}]}',  # empty service name
        '{"steps":[{"s":"a","in":[""],"next":[]}]}',  # empty key
    ]:
        assert not g.is_accept(g.walk(text)), text


def test_mask_matches_transitions():
    g = build_plan_grammar()
    tok = ByteTokenizer()
    # Wherever mask is True (except EOS in accept), transition is not dead.
    live = g.mask.copy()
    live[:, tok.eos_id] = False
    assert np.all(g.transitions[live] != g.dead_state)
    # Dead state allows nothing.
    assert not g.mask[g.dead_state].any()
    # PAD never allowed, self-loops everywhere.
    assert not g.mask[:, tok.pad_id].any()
    assert np.array_equal(g.transitions[:, tok.pad_id], np.arange(g.n_states))


def test_greedy_walk_emits_valid_json():
    """Following any allowed token from start must eventually be able to
    reach accept: simulate a random-but-legal walk and parse the result."""
    rng = np.random.default_rng(0)
    g = build_plan_grammar()
    tok = ByteTokenizer()
    state = g.start_state
    out = []
    closers = [tok.eos_id, ord('"'), ord("]"), ord("}")]
    for _ in range(600):
        allowed = set(np.flatnonzero(g.mask[state]).tolist())
        assert allowed, f"stuck at state {state} after {len(out)} bytes"
        out_tok = None
        # After a while, prefer closing constructs so the walk terminates.
        if len(out) > 60:
            for c in closers:
                if c in allowed:
                    out_tok = c
                    break
        if out_tok is None:
            out_tok = int(rng.choice(sorted(allowed)))
        if out_tok == tok.eos_id:
            break
        out.append(out_tok)
        state = int(g.transitions[state, out_tok])
    text = tok.decode(out)
    assert g.is_accept(g.walk(text)), text
    # The grammar guarantees *structure*: always-parseable JSON in the steps
    # shape. Referential integrity (next-steps naming real steps) is the LLM
    # planner's bounded-retry responsibility, not the DFA's.
    import json

    obj = json.loads(text)
    assert isinstance(obj["steps"], list) and obj["steps"]
    assert all(set(s) == {"s", "in", "next"} for s in obj["steps"])


def test_compact_keys_parse_to_plan():
    text = '{"steps":[{"s":"fetch","in":["query"],"next":["rank"]},{"s":"rank","in":["doc"],"next":[]}]}'
    plan = Plan.from_json(text)
    assert [n.name for n in plan.nodes] == ["fetch", "rank"]
    assert plan.topological_generations() == [["fetch"], ["rank"]]


def test_distance_to_accept():
    """dist[s] must be the exact shortest completion length: simulate the
    greedy 'always move closer' walk from every reachable state and check it
    finishes in exactly dist[s] samples."""
    g = build_plan_grammar()
    tok = ByteTokenizer()
    inf = np.iinfo(np.int32).max // 2
    # Accept states are one EOS sample away.
    for s in g.accept_states:
        assert g.dist[s] == 1
    assert g.dist[g.dead_state] >= inf
    assert g.min_len == g.dist[g.start_state]
    # The shortest valid plan really is min_len bytes + EOS.
    shortest = '{"steps":[{"s":"?","in":[],"next":[]}]}'
    assert g.is_accept(g.walk(shortest))
    assert g.min_len == len(shortest) + 1
    # Greedy-descent from every live reachable state terminates in dist[s].
    reachable = {g.start_state}
    frontier = [g.start_state]
    while frontier:
        nxt = []
        for s in frontier:
            for b in np.flatnonzero(g.mask[s]):
                t = int(g.transitions[s, b])
                if t != g.dead_state and t not in reachable:
                    reachable.add(t)
                    nxt.append(t)
        frontier = nxt
    for s in sorted(reachable):
        d = int(g.dist[s])
        assert d < inf, f"reachable state {s} cannot finish"
        state, taken = s, 0
        while state not in g.accept_states:
            allowed = np.flatnonzero(g.mask[state])
            succ = [
                (int(g.dist[int(g.transitions[state, b])]), int(b))
                for b in allowed
                if b != tok.eos_id
            ]
            db, b = min(succ)
            assert db == int(g.dist[state]) - 1  # BFS consistency
            state = int(g.transitions[state, b])
            taken += 1
        assert taken + 1 == d, f"state {s}: took {taken}+EOS, dist={d}"


def test_budget_mask_never_strands():
    """Emulate the engine's budget mask host-side: any walk that only takes
    tokens allowed by (grammar AND budget) finishes within the budget."""
    rng = np.random.default_rng(1)
    g = build_plan_grammar()
    tok = ByteTokenizer()
    for budget in [g.min_len, g.min_len + 1, g.min_len + 7, 96]:
        for trial in range(20):
            state, emitted, text = g.start_state, 0, []
            while True:
                rem = budget - emitted - 1  # samples left after this one
                allowed = [
                    int(b)
                    for b in np.flatnonzero(g.mask[state])
                    if b == tok.eos_id or int(g.dist[int(g.transitions[state, b])]) <= rem
                ]
                assert allowed, f"stranded at {state} budget={budget} emitted={emitted}"
                b = int(rng.choice(allowed))
                emitted += 1
                if b == tok.eos_id:
                    break
                text.append(b)
                state = int(g.transitions[state, b])
                assert emitted < budget, "budget exceeded without EOS"
            decoded = tok.decode(text)
            assert g.is_accept(g.walk(decoded)), decoded


class ToySubwordTokenizer:
    """Synthetic multi-byte tokenizer (SentencePiece stand-in): all single
    bytes plus merged JSON-structure fragments and service-name pieces —
    exercises the grammar's token-DFA product without external model files."""

    MERGES = [b'{"steps":[{"s":"', b'","in":[', b'"],"next":[', b'"]}',
              b'auth', b'fetch', b'-00', b'"]},{"s":"', b'{"s":"', b'": "', b'xyz']

    def __init__(self):
        self._pieces = [bytes([i]) for i in range(256)] + list(self.MERGES)
        self.pad_id = len(self._pieces)
        self.bos_id = self.pad_id + 1
        self.eos_id = self.pad_id + 2
        raw = self.eos_id + 1
        self.vocab_size = ((raw + 127) // 128) * 128

    def token_bytes(self):
        out = list(self._pieces)
        out += [None] * (self.vocab_size - len(out))
        return out

    def encode(self, text, *, bos=True, eos=False):
        data = text.encode("utf-8")
        ids, i = ([self.bos_id] if bos else []), 0
        by_len = sorted(range(256, len(self._pieces)), key=lambda t: -len(self._pieces[t]))
        while i < len(data):
            for t in by_len:
                p = self._pieces[t]
                if data.startswith(p, i):
                    ids.append(t)
                    i += len(p)
                    break
            else:
                ids.append(data[i])
                i += 1
        return ids + ([self.eos_id] if eos else [])

    def decode(self, ids):
        return b"".join(self._pieces[i] for i in ids if 0 <= i < len(self._pieces)).decode(
            "utf-8", errors="replace"
        )


def test_subword_product_matches_byte_walk():
    """Token-level transitions == walking each token's bytes through the
    byte DFA, for every (state, token)."""
    tok = ToySubwordTokenizer()
    g = build_plan_grammar(tok)
    tb = tok.token_bytes()
    rng = np.random.default_rng(0)
    states = rng.integers(0, g.n_states, size=40)
    tokens = list(rng.integers(0, tok.vocab_size, size=60)) + [256, 257, 258, 259, 263]
    for s in states:
        for t in tokens:
            b = tb[t]
            if t in (tok.eos_id, tok.pad_id) or b is None or not b:
                continue
            expect = int(s)
            for byte in b:
                expect = int(g.byte_transitions[expect, byte])
            assert int(g.transitions[s, t]) == expect, (s, t, b)
            assert bool(g.mask[s, t]) == (expect != g.dead_state)


def test_subword_constrained_walk_emits_valid_json():
    """A constrained greedy walk over the SUBWORD vocab must emit bytes the
    grammar accepts — multi-byte fragments included — and round-trip
    through Plan.from_json."""
    import json as _json
    import random

    tok = ToySubwordTokenizer()
    g = build_plan_grammar(tok)
    rng = random.Random(5)
    for trial in range(10):
        state, ids, emitted = g.start_state, [], 0
        budget = 96
        while True:
            rem = budget - emitted - 1
            allowed = [
                int(t)
                for t in np.flatnonzero(g.mask[state])
                if t == tok.eos_id or int(g.dist[int(g.transitions[state, t])]) <= rem
            ]
            assert allowed, f"stranded at {state}"
            t = rng.choice(allowed)
            emitted += 1
            if t == tok.eos_id:
                break
            ids.append(t)
            state = int(g.transitions[state, t])
        decoded = tok.decode(ids)
        assert g.is_accept(g.walk(decoded)), decoded
        _json.loads(decoded)


def test_subword_dist_counts_samples_not_bytes():
    """min_len over a subword vocab must be <= the byte vocab's min_len:
    merged fragments cover several bytes per sample."""
    byte_g = build_plan_grammar(ByteTokenizer())
    sub_g = build_plan_grammar(ToySubwordTokenizer())
    assert sub_g.min_len <= byte_g.min_len
    assert sub_g.min_len >= 4  # still needs items + closes + EOS


def test_byte_tokenizer_product_is_identity_lift():
    """For the byte tokenizer the token DFA must equal the byte DFA on byte
    ids (the product is the identity lift)."""
    tok = ByteTokenizer()
    g = build_plan_grammar(tok)
    np.testing.assert_array_equal(g.transitions[:, :256], g.byte_transitions)


# --- registry-constrained name tries (VERDICT r1 #2) -----------------------


def test_trie_accepts_only_listed_names():
    tok = ByteTokenizer()
    names = ["auth-fetch", "auth-verify", "billing", "notify"]
    g = build_plan_grammar(tok, names)
    assert g.service_names == tuple(sorted(names))
    ok = '{"steps":[{"s":"auth-fetch","in":["q"],"next":["notify"]}]}'
    assert g.is_accept(g.walk(ok))
    # unknown service name in "s" or "next" dies mid-string
    assert g.walk('{"steps":[{"s":"auth-zzz","in":[],"next":[]}]}') == g.dead_state
    assert g.walk('{"steps":[{"s":"billing","in":[],"next":["ghost"]}]}') == g.dead_state
    # truncated legal prefix cannot close the string
    assert g.walk('{"steps":[{"s":"auth","in":[],"next":[]}]}') == g.dead_state
    # "in" keys stay free-form
    assert g.is_accept(g.walk('{"steps":[{"s":"billing","in":["anything at all"],"next":[]}]}'))


def test_typed_grammar_only_admits_schema_valid_bodies():
    """Typed-dataflow construction: each step's body is conditioned on the
    service its "s" named — "in" admits only that service's own input keys,
    "next" only services one of its outputs feeds (no self). Incoherent
    edges are UNREPRESENTABLE, extending the registry-name guarantee to
    dataflow validity (the shortlist serving tier's grammar)."""
    from mcpx.registry.base import ServiceRecord

    recs = [
        ServiceRecord(
            name="fetch",
            endpoint="local://fetch",
            input_schema={"query": "str"},
            output_schema={"data": "str"},
        ),
        ServiceRecord(
            name="summarize",
            endpoint="local://sum",
            input_schema={"data": "str"},
            output_schema={"summary": "str"},
        ),
        ServiceRecord(
            name="audit",
            endpoint="local://audit",
            input_schema={"report": "str"},
            output_schema={},
        ),
    ]
    g = build_plan_grammar(ByteTokenizer(), services=recs)
    assert g.service_names == tuple(sorted(r.name for r in recs))
    # Schema-valid: fetch(data) -> summarize(data->summary); own keys only.
    ok = (
        '{"steps":[{"s":"fetch","in":["query"],"next":["summarize"]},'
        '{"s":"summarize","in":["data"],"next":[]}]}'
    )
    assert g.is_accept(g.walk(ok))
    # fetch's outputs feed NO input of audit: the edge is unrepresentable.
    assert g.walk('{"steps":[{"s":"fetch","in":[],"next":["audit"]}]}') == g.dead_state
    # "in" is typed per-service: fetch has no "data" input.
    assert g.walk('{"steps":[{"s":"fetch","in":["data"],"next":[]}]}') == g.dead_state
    # No self-edges, even when schemas would chain.
    assert g.walk('{"steps":[{"s":"fetch","in":[],"next":["fetch"]}]}') == g.dead_state
    # audit produces nothing -> its "next" can only be the empty list.
    assert g.is_accept(g.walk('{"steps":[{"s":"audit","in":["report"],"next":[]}]}'))
    assert (
        g.walk('{"steps":[{"s":"audit","in":["report"],"next":["fetch"]}]}')
        == g.dead_state
    )
    # Empty "in" stays legal everywhere (payload-only steps).
    assert g.is_accept(g.walk('{"steps":[{"s":"summarize","in":[],"next":[]}]}'))


def test_typed_grammar_greedy_walks_stay_schema_valid():
    """Every token-greedy path through the typed tables decodes to a plan
    whose edges ALL typecheck — the structural claim the shortlist tier's
    coherence rests on."""
    import json as _json
    import random

    from mcpx.registry.base import ServiceRecord
    from mcpx.utils.synth import synth_registry

    recs = synth_registry(6, seed=3)
    by_name = {r.name: r for r in recs}
    g = build_plan_grammar(ByteTokenizer(), services=recs)
    rng = random.Random(0)
    for _ in range(25):
        state, out = g.start_state, []
        for _step in range(220):
            legal = [c for c in range(g.cmask.shape[1]) if g.cmask[state, c]]
            col = rng.choice(legal)
            if g.eos_cols[col]:
                break
            out.append(int(g.active_ids[col]))
            state = int(g.ctrans[state, col])
        else:
            continue  # walk didn't terminate: skip (budget tests cover it)
        obj = _json.loads(ByteTokenizer().decode(out))
        for step in obj["steps"]:
            src = by_name[step["s"]]
            assert set(step["in"]) <= set(src.input_schema)
            for nxt in step["next"]:
                assert set(src.output_schema) & set(by_name[nxt].input_schema)
                assert nxt != step["s"]


def test_trie_prefix_name_branches_on_quote():
    g = build_plan_grammar(ByteTokenizer(), ["auth", "auth-fetch"])
    assert g.is_accept(g.walk('{"steps":[{"s":"auth","in":[],"next":["auth-fetch"]}]}'))
    assert g.is_accept(g.walk('{"steps":[{"s":"auth-fetch","in":[],"next":["auth"]}]}'))
    assert g.walk('{"steps":[{"s":"auth-","in":[],"next":[]}]}') == g.dead_state


def test_trie_random_legal_walk_names_only_registry_services():
    """Any mask-legal walk through a trie grammar must terminate in plans
    whose every service name is a listed one — the decode-time guarantee
    the planner's accept path relies on."""
    import json as _json

    rng = np.random.default_rng(3)
    tok = ByteTokenizer()
    names = ["svc-alpha", "svc-beta", "other-gamma"]
    g = build_plan_grammar(tok, names)
    for trial in range(5):
        state = g.start_state
        ids = []
        emitted = 0
        while emitted < 300:
            rem = 300 - emitted
            allowed = [
                int(t)
                for t in np.flatnonzero(g.mask[state])
                if t == tok.eos_id or int(g.dist[int(g.transitions[state, t])]) <= rem
            ]
            assert allowed, f"stranded at {state}"
            t = int(rng.choice(allowed))
            emitted += 1
            if t == tok.eos_id:
                break
            ids.append(t)
            state = int(g.transitions[state, t])
        text = tok.decode(ids)
        assert g.is_accept(g.walk(text)), text
        obj = _json.loads(text)
        for step in obj["steps"]:
            assert step["s"] in names
            assert all(nx in names for nx in step["next"])


def test_trie_rejects_unencodable_names():
    import pytest

    with pytest.raises(ValueError):
        build_plan_grammar(ByteTokenizer(), ['has"quote'])
    with pytest.raises(ValueError):
        build_plan_grammar(ByteTokenizer(), [""])


def test_device_tables_pad_and_share():
    tok = ByteTokenizer()
    g = build_plan_grammar(tok, ["a-svc", "b-svc"])
    trans, mask, dist, active_ids, eos_cols, inv_cols = g.device_tables()
    n, c = g.ctrans.shape
    assert trans.shape[0] % 512 == 0 and trans.shape[0] >= n
    assert trans.shape[1] >= c and trans.shape == mask.shape
    assert dist.shape[0] == trans.shape[0]
    assert active_ids.shape == eos_cols.shape == (trans.shape[1],)
    # same objects on second call (one HBM copy per grammar)
    t2 = g.device_tables()
    assert t2[0] is trans and t2[1] is mask and t2[2] is dist
    # padded rows/cols: unreachable, all-False mask, dead transitions
    assert not bool(np.asarray(mask)[n:].any())
    assert not bool(np.asarray(mask)[:, c:].any())
    assert np.all(np.asarray(trans)[n:] == g.cdead)
    # real rows match compact host tables, which match the dense tables'
    # active columns (dense path keeps both forms coherent)
    np.testing.assert_array_equal(np.asarray(trans)[:n, :c], g.ctrans)
    np.testing.assert_array_equal(np.asarray(mask)[:n, :c], g.cmask)
    np.testing.assert_array_equal(np.asarray(dist)[:n], g.dist)
    np.testing.assert_array_equal(g.ctrans, g.transitions[:, g.active_ids])
    np.testing.assert_array_equal(g.cmask, g.mask[:, g.active_ids])
    # EOS is an active column; PAD never is
    assert tok.eos_id in g.active_ids
    assert tok.pad_id not in g.active_ids
    assert bool(g.eos_cols[np.flatnonzero(g.active_ids == tok.eos_id)[0]])
    # inv_cols is the exact inverse of active_ids; inactive ids map to -1
    inv_np = np.asarray(inv_cols)
    assert inv_np.shape == (tok.vocab_size,)
    np.testing.assert_array_equal(inv_np[g.active_ids], np.arange(c))
    assert inv_np[tok.pad_id] == -1


def test_engine_pad_makes_registry_grammar_share_warmup_shape():
    """The engine's pad quanta must give the generic grammar and a realistic
    registry trie identical padded table shapes — that equality is what lets
    the warmup-compiled decode executable serve real requests without an
    in-path XLA compile."""
    from mcpx.engine.engine import InferenceEngine

    eng = InferenceEngine()
    pad = eng._grammar_pad()
    generic = eng.grammar.device_tables(pad)
    names = [f"svc-{kind}-{i:04d}" for kind in ("fetch", "rank", "notify") for i in range(50)]
    trie = build_plan_grammar(ByteTokenizer(), names)
    dev = trie.device_tables(pad)
    for a, b in zip(generic, dev):
        assert a.shape == b.shape


def _subword_tok(pieces: list[str], vocab_pad: int = 0):
    """Minimal multi-byte-token tokenizer for exercising the grammar product
    on subword vocabs without external files: bytes 0..255 are always
    present (byte fallback), then the given pieces, then PAD/BOS/EOS."""

    class SubwordTok:
        def __init__(self) -> None:
            self.pieces = [bytes([i]) for i in range(256)] + [
                p.encode("utf-8") for p in pieces
            ]
            self.pad_id = len(self.pieces)
            self.bos_id = self.pad_id + 1
            self.eos_id = self.pad_id + 2
            self.vocab_size = self.pad_id + 3 + vocab_pad

        def token_bytes(self):
            out = list(self.pieces)
            out += [None] * (self.vocab_size - len(out))
            return out

        def decode(self, ids):
            data = b"".join(
                self.pieces[i] for i in ids if 0 <= i < len(self.pieces)
            )
            return data.decode("utf-8", errors="replace")

    return SubwordTok()


def test_sparse_product_matches_dense():
    """The sparse BFS product (huge-vocab path) must accept exactly the same
    strings as the dense product: equal min_len, equal legal-token sets
    along a greedy walk, and a full emitted plan that byte-walks to accept."""
    import mcpx.planner.grammar as G

    names = ["alpha-svc", "alpine-svc", "beta"]
    keys = ["user_id", "query"]
    pieces = ['{"steps":[{"s":"', 'alpha', '-svc', '","in":[', '"user_id"',
              '],"next":[', ']}', ']}'[0], 'alp', 'beta', '"query"', '",']
    tok = _subword_tok(pieces)
    dense = G.build_plan_grammar(tok, names, input_keys=keys)
    assert dense.transitions is not None  # small vocab -> dense path

    # Force the sparse path by shrinking the dense-entries budgets
    # (subword vocabs gate on _DENSE_SUBWORD_MAX since the BPE speedup).
    old = G._DENSE_ENTRIES_MAX, G._DENSE_SUBWORD_MAX
    G._DENSE_ENTRIES_MAX = G._DENSE_SUBWORD_MAX = 1
    try:
        sparse = G.build_plan_grammar(tok, names, input_keys=keys)
    finally:
        G._DENSE_ENTRIES_MAX, G._DENSE_SUBWORD_MAX = old
    assert sparse.transitions is None  # sparse path taken

    assert sparse.min_len == dense.min_len
    # Same active token set.
    np.testing.assert_array_equal(sparse.active_ids, dense.active_ids)

    # Greedy forced-completion walk through BOTH automata emits identical
    # token sequences and lands in accept.
    def emit(g):
        st, out = g.start_state, []
        for _ in range(200):
            legal = np.flatnonzero(g.cmask[st])
            assert legal.size, (st, out)
            # prefer EOS when legal, else smallest finishing column
            eos_legal = [c for c in legal if g.eos_cols[c]]
            if eos_legal:
                return out, True
            c = min(legal, key=lambda c: int(g.dist[int(g.ctrans[st, c])]))
            out.append(int(g.active_ids[c]))
            st = int(g.ctrans[st, c])
        return out, False

    toks_d, done_d = emit(dense)
    toks_s, done_s = emit(sparse)
    assert done_d and done_s
    assert toks_d == toks_s
    text = tok.decode(toks_d)
    assert dense.is_accept(dense.walk(text)), text
    assert sparse.is_accept(sparse.walk(text)), text


def test_sparse_free_strings_exceed_budget():
    """Free-string positions on a large vocab must raise (the planner then
    falls back through key tries to the shape-only grammar) rather than
    building an enormous table."""
    import mcpx.planner.grammar as G

    tok = _subword_tok([f"piece{i}" for i in range(50)])
    old_dense = G._DENSE_ENTRIES_MAX, G._DENSE_SUBWORD_MAX
    old_budget = G._SPARSE_VISIT_BUDGET
    G._DENSE_ENTRIES_MAX = G._DENSE_SUBWORD_MAX = 1
    G._SPARSE_VISIT_BUDGET = 300
    try:
        import pytest

        with pytest.raises(ValueError, match="budget"):
            # names trie'd but "in" keys free -> permissive states blow the
            # visit budget at this (artificially tiny) setting
            G.build_plan_grammar(tok, ["alpha-svc"])
    finally:
        G._DENSE_ENTRIES_MAX, G._DENSE_SUBWORD_MAX = old_dense
        G._SPARSE_VISIT_BUDGET = old_budget


def test_stacked_tables_step_identical_to_single():
    """Heterogeneous batching stacks several grammars' compact tables along
    a leading slot axis (engine per-row dfa_id indexing). Stepping through
    the stacked tables must be token-for-token identical to stepping the
    original per-grammar tables — legal sets, transitions, eos columns,
    active ids and distance-to-accept all agree at every state of random
    legal walks, per grammar, per slot."""
    import random

    from mcpx.planner.grammar import build_trivial_grammar, stacked_tables

    tok = ByteTokenizer()
    g_plain = build_plan_grammar(tok)
    g_trie = build_plan_grammar(tok, ["svc-a", "svc-b", "other-name"])
    triv = build_trivial_grammar(tok)
    strans, smask, sdist, sids, seos = stacked_tables([triv, g_plain, g_trie])
    assert strans.shape[0] == 3 and strans.shape == smask.shape
    for gi, g in ((1, g_plain), (2, g_trie)):
        C = g.n_active
        assert np.array_equal(sids[gi, :C], g.active_ids)
        assert np.array_equal(seos[gi, :C], g.eos_cols)
        assert not smask[gi, :, C:].any()  # padding columns inert
        assert np.array_equal(sdist[gi, : g.n_states], g.dist)
        rng = random.Random(gi)
        for _walk in range(10):
            s = g.start_state
            for _step in range(80):
                legal = np.flatnonzero(g.cmask[s])
                assert np.array_equal(legal, np.flatnonzero(smask[gi, s]))
                if len(legal) == 0:
                    break
                c = int(rng.choice(list(legal)))
                if g.eos_cols[c]:
                    break
                nxt = int(g.ctrans[s, c])
                assert nxt == int(strans[gi, s, c])
                s = nxt


def test_trivial_grammar_never_forces_and_accepts_everything():
    """The trivial slot-0 DFA (unconstrained rows): grammar fast-forward
    forces a token only when exactly ONE column is legal, so no trivial
    state may have a single-column mask; host-side walk accepts any text."""
    from mcpx.planner.grammar import build_trivial_grammar

    g = build_trivial_grammar()
    assert not (g.cmask.sum(axis=1) == 1).any()
    for text in ["", "anything at all", '{"not":"a plan"}', "\x00\xff"]:
        assert g.is_accept(g.walk(text)) or g.walk(text) == g.start_state
    assert g.is_accept(g.walk("free text"))
