"""Grammar DFA tests: acceptance, rejection, mask/transition table
consistency, and device-side constrained sampling (SURVEY.md §4.1)."""

import numpy as np

from mcpx.core.dag import Plan
from mcpx.models.tokenizer import ByteTokenizer
from mcpx.planner.grammar import build_plan_grammar


def test_accepts_valid_plans():
    g = build_plan_grammar()
    for text in [
        '{"steps":[{"s":"search","in":["query"],"next":["sum"]},{"s":"sum","in":[],"next":[]}]}',
        '{"steps":[{"s":"a","in":[],"next":[]}]}',
        '{"steps":[{"s":"a","in":["x","y"],"next":[]},{"s":"b","in":[],"next":[]}]}',
    ]:
        final = g.walk(text)
        assert g.is_accept(final), text
        # And what the grammar accepts, the Plan parser accepts.
        plan = Plan.from_json(text)
        assert plan.nodes


def test_rejects_invalid():
    g = build_plan_grammar()
    for text in [
        '{"steps":[]}',  # empty steps not allowed
        '{"steps":[{"s":"a"}]}',  # missing keys
        '{"nodes":[]}',  # wrong envelope
        '{"steps":[{"s":"a","in":[],"next":[]}]',  # unterminated
        'plain text',
        '{"steps":[{"s":"a\\"","in":[],"next":[]}]}',  # escape rejected
        '{"steps":[{"s":"","in":[],"next":[]}]}',  # empty service name
        '{"steps":[{"s":"a","in":[""],"next":[]}]}',  # empty key
    ]:
        assert not g.is_accept(g.walk(text)), text


def test_mask_matches_transitions():
    g = build_plan_grammar()
    tok = ByteTokenizer()
    # Wherever mask is True (except EOS in accept), transition is not dead.
    live = g.mask.copy()
    live[:, tok.eos_id] = False
    assert np.all(g.transitions[live] != g.dead_state)
    # Dead state allows nothing.
    assert not g.mask[g.dead_state].any()
    # PAD never allowed, self-loops everywhere.
    assert not g.mask[:, tok.pad_id].any()
    assert np.array_equal(g.transitions[:, tok.pad_id], np.arange(g.n_states))


def test_greedy_walk_emits_valid_json():
    """Following any allowed token from start must eventually be able to
    reach accept: simulate a random-but-legal walk and parse the result."""
    rng = np.random.default_rng(0)
    g = build_plan_grammar()
    tok = ByteTokenizer()
    state = g.start_state
    out = []
    closers = [tok.eos_id, ord('"'), ord("]"), ord("}")]
    for _ in range(600):
        allowed = set(np.flatnonzero(g.mask[state]).tolist())
        assert allowed, f"stuck at state {state} after {len(out)} bytes"
        out_tok = None
        # After a while, prefer closing constructs so the walk terminates.
        if len(out) > 60:
            for c in closers:
                if c in allowed:
                    out_tok = c
                    break
        if out_tok is None:
            out_tok = int(rng.choice(sorted(allowed)))
        if out_tok == tok.eos_id:
            break
        out.append(out_tok)
        state = int(g.transitions[state, out_tok])
    text = tok.decode(out)
    assert g.is_accept(g.walk(text)), text
    # The grammar guarantees *structure*: always-parseable JSON in the steps
    # shape. Referential integrity (next-steps naming real steps) is the LLM
    # planner's bounded-retry responsibility, not the DFA's.
    import json

    obj = json.loads(text)
    assert isinstance(obj["steps"], list) and obj["steps"]
    assert all(set(s) == {"s", "in", "next"} for s in obj["steps"])


def test_compact_keys_parse_to_plan():
    text = '{"steps":[{"s":"fetch","in":["query"],"next":["rank"]},{"s":"rank","in":["doc"],"next":[]}]}'
    plan = Plan.from_json(text)
    assert [n.name for n in plan.nodes] == ["fetch", "rank"]
    assert plan.topological_generations() == [["fetch"], ["rank"]]
