"""Grammar DFA tests: acceptance, rejection, mask/transition table
consistency, and device-side constrained sampling (SURVEY.md §4.1)."""

import numpy as np

from mcpx.core.dag import Plan
from mcpx.models.tokenizer import ByteTokenizer
from mcpx.planner.grammar import build_plan_grammar


def test_accepts_valid_plans():
    g = build_plan_grammar()
    for text in [
        '{"steps":[{"s":"search","in":["query"],"next":["sum"]},{"s":"sum","in":[],"next":[]}]}',
        '{"steps":[{"s":"a","in":[],"next":[]}]}',
        '{"steps":[{"s":"a","in":["x","y"],"next":[]},{"s":"b","in":[],"next":[]}]}',
    ]:
        final = g.walk(text)
        assert g.is_accept(final), text
        # And what the grammar accepts, the Plan parser accepts.
        plan = Plan.from_json(text)
        assert plan.nodes


def test_rejects_invalid():
    g = build_plan_grammar()
    for text in [
        '{"steps":[]}',  # empty steps not allowed
        '{"steps":[{"s":"a"}]}',  # missing keys
        '{"nodes":[]}',  # wrong envelope
        '{"steps":[{"s":"a","in":[],"next":[]}]',  # unterminated
        'plain text',
        '{"steps":[{"s":"a\\"","in":[],"next":[]}]}',  # escape rejected
        '{"steps":[{"s":"","in":[],"next":[]}]}',  # empty service name
        '{"steps":[{"s":"a","in":[""],"next":[]}]}',  # empty key
    ]:
        assert not g.is_accept(g.walk(text)), text


def test_mask_matches_transitions():
    g = build_plan_grammar()
    tok = ByteTokenizer()
    # Wherever mask is True (except EOS in accept), transition is not dead.
    live = g.mask.copy()
    live[:, tok.eos_id] = False
    assert np.all(g.transitions[live] != g.dead_state)
    # Dead state allows nothing.
    assert not g.mask[g.dead_state].any()
    # PAD never allowed, self-loops everywhere.
    assert not g.mask[:, tok.pad_id].any()
    assert np.array_equal(g.transitions[:, tok.pad_id], np.arange(g.n_states))


def test_greedy_walk_emits_valid_json():
    """Following any allowed token from start must eventually be able to
    reach accept: simulate a random-but-legal walk and parse the result."""
    rng = np.random.default_rng(0)
    g = build_plan_grammar()
    tok = ByteTokenizer()
    state = g.start_state
    out = []
    closers = [tok.eos_id, ord('"'), ord("]"), ord("}")]
    for _ in range(600):
        allowed = set(np.flatnonzero(g.mask[state]).tolist())
        assert allowed, f"stuck at state {state} after {len(out)} bytes"
        out_tok = None
        # After a while, prefer closing constructs so the walk terminates.
        if len(out) > 60:
            for c in closers:
                if c in allowed:
                    out_tok = c
                    break
        if out_tok is None:
            out_tok = int(rng.choice(sorted(allowed)))
        if out_tok == tok.eos_id:
            break
        out.append(out_tok)
        state = int(g.transitions[state, out_tok])
    text = tok.decode(out)
    assert g.is_accept(g.walk(text)), text
    # The grammar guarantees *structure*: always-parseable JSON in the steps
    # shape. Referential integrity (next-steps naming real steps) is the LLM
    # planner's bounded-retry responsibility, not the DFA's.
    import json

    obj = json.loads(text)
    assert isinstance(obj["steps"], list) and obj["steps"]
    assert all(set(s) == {"s", "in", "next"} for s in obj["steps"])


def test_compact_keys_parse_to_plan():
    text = '{"steps":[{"s":"fetch","in":["query"],"next":["rank"]},{"s":"rank","in":["doc"],"next":[]}]}'
    plan = Plan.from_json(text)
    assert [n.name for n in plan.nodes] == ["fetch", "rank"]
    assert plan.topological_generations() == [["fetch"], ["rank"]]


def test_distance_to_accept():
    """dist[s] must be the exact shortest completion length: simulate the
    greedy 'always move closer' walk from every reachable state and check it
    finishes in exactly dist[s] samples."""
    g = build_plan_grammar()
    tok = ByteTokenizer()
    inf = np.iinfo(np.int32).max // 2
    # Accept states are one EOS sample away.
    for s in g.accept_states:
        assert g.dist[s] == 1
    assert g.dist[g.dead_state] >= inf
    assert g.min_len == g.dist[g.start_state]
    # The shortest valid plan really is min_len bytes + EOS.
    shortest = '{"steps":[{"s":"?","in":[],"next":[]}]}'
    assert g.is_accept(g.walk(shortest))
    assert g.min_len == len(shortest) + 1
    # Greedy-descent from every live reachable state terminates in dist[s].
    reachable = {g.start_state}
    frontier = [g.start_state]
    while frontier:
        nxt = []
        for s in frontier:
            for b in np.flatnonzero(g.mask[s]):
                t = int(g.transitions[s, b])
                if t != g.dead_state and t not in reachable:
                    reachable.add(t)
                    nxt.append(t)
        frontier = nxt
    for s in sorted(reachable):
        d = int(g.dist[s])
        assert d < inf, f"reachable state {s} cannot finish"
        state, taken = s, 0
        while state not in g.accept_states:
            allowed = np.flatnonzero(g.mask[state])
            succ = [
                (int(g.dist[int(g.transitions[state, b])]), int(b))
                for b in allowed
                if b != tok.eos_id
            ]
            db, b = min(succ)
            assert db == int(g.dist[state]) - 1  # BFS consistency
            state = int(g.transitions[state, b])
            taken += 1
        assert taken + 1 == d, f"state {s}: took {taken}+EOS, dist={d}"


def test_budget_mask_never_strands():
    """Emulate the engine's budget mask host-side: any walk that only takes
    tokens allowed by (grammar AND budget) finishes within the budget."""
    rng = np.random.default_rng(1)
    g = build_plan_grammar()
    tok = ByteTokenizer()
    for budget in [g.min_len, g.min_len + 1, g.min_len + 7, 96]:
        for trial in range(20):
            state, emitted, text = g.start_state, 0, []
            while True:
                rem = budget - emitted - 1  # samples left after this one
                allowed = [
                    int(b)
                    for b in np.flatnonzero(g.mask[state])
                    if b == tok.eos_id or int(g.dist[int(g.transitions[state, b])]) <= rem
                ]
                assert allowed, f"stranded at {state} budget={budget} emitted={emitted}"
                b = int(rng.choice(allowed))
                emitted += 1
                if b == tok.eos_id:
                    break
                text.append(b)
                state = int(g.transitions[state, b])
                assert emitted < budget, "budget exceeded without EOS"
            decoded = tok.decode(text)
            assert g.is_accept(g.walk(decoded)), decoded
