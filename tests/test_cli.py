"""CLI surface: validate/gen-registry round trip, config plumbing, and a
serve smoke test (the reference's only entry point is a bare uvicorn dev
block, ``control_plane.py:155-157``)."""

import asyncio
import json

from mcpx.cli.main import main


def test_gen_registry_then_serve_smoke(tmp_path, capsys):
    reg_path = tmp_path / "registry.json"
    assert main(["gen-registry", "5", "--out", str(reg_path), "--seed", "3"]) == 0
    records = json.loads(reg_path.read_text())
    assert len(records) == 5
    assert all({"name", "endpoint"} <= set(r) for r in records)

    # The file registry + heuristic planner serve end-to-end over HTTP.
    async def go():
        from aiohttp import ClientSession
        from aiohttp.test_utils import TestServer

        from mcpx.cli.main import _load_config
        from mcpx.server.app import build_app
        from mcpx.server.factory import build_control_plane

        import argparse

        args = argparse.Namespace(
            config=None, registry_file=str(reg_path), planner="heuristic"
        )
        cfg = _load_config(args)
        assert cfg.registry.backend == "file"
        cp = build_control_plane(cfg)
        server = TestServer(build_app(cp))
        await server.start_server()
        try:
            async with ClientSession() as s:
                async with s.get(
                    f"http://{server.host}:{server.port}/services"
                ) as r:
                    body = await r.json()
                assert r.status == 200 and len(body["services"]) == 5
                async with s.post(
                    f"http://{server.host}:{server.port}/plan",
                    json={"intent": f"use {records[0]['name']}"},
                ) as r:
                    assert r.status == 200
                    plan = await r.json()
                assert plan["graph"]["nodes"]
        finally:
            await server.close()

    asyncio.run(go())


def test_validate_accepts_and_rejects(tmp_path, capsys):
    good = tmp_path / "good.json"
    good.write_text(
        json.dumps(
            {"nodes": [{"name": "a"}, {"name": "b"}], "edges": [{"from": "a", "to": "b"}]}
        )
    )
    assert main(["validate", str(good)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["valid"] and out["generations"] == [["a"], ["b"]]

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"nodes": [{"name": "a"}], "edges": [{"from": "a", "to": "ghost"}]}))
    assert main(["validate", str(bad)]) == 1
    out = json.loads(capsys.readouterr().out)
    assert not out["valid"] and out["problems"]


def test_config_file_plumbing(tmp_path):
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(json.dumps({"server": {"port": 9123}, "planner": {"kind": "mock"}}))
    import argparse

    from mcpx.cli.main import _load_config

    cfg = _load_config(argparse.Namespace(config=str(cfg_path), registry_file=None, planner=None))
    assert cfg.server.port == 9123 and cfg.planner.kind == "mock"


def test_explain_cli_defaults_to_newest_trace(tmp_path, capsys):
    """``mcpx explain`` with no trace id explains the newest retained
    trace — the "what just happened" workflow, alongside ``mcpx debug``."""
    reg_path = tmp_path / "registry.json"
    assert main(["gen-registry", "3", "--out", str(reg_path), "--seed", "7"]) == 0
    records = json.loads(reg_path.read_text())

    async def go():
        from aiohttp import ClientSession
        from aiohttp.test_utils import TestServer

        from mcpx.cli.main import _load_config
        from mcpx.server.app import build_app
        from mcpx.server.factory import build_control_plane

        import argparse

        args = argparse.Namespace(
            config=None, registry_file=str(reg_path), planner="heuristic"
        )
        cfg = _load_config(args)
        cfg.telemetry.provenance.enabled = True
        cp = build_control_plane(cfg)
        server = TestServer(build_app(cp))
        await server.start_server()
        base = f"http://{server.host}:{server.port}"
        try:
            async with ClientSession() as s:
                async with s.post(
                    f"{base}/plan", json={"intent": f"use {records[0]['name']}"}
                ) as r:
                    assert r.status == 200
            out_path = str(tmp_path / "explained.json")
            rc = await asyncio.to_thread(
                main, ["explain", "--url", base, "--out", out_path]
            )
            assert rc == 0
            explanation = json.loads((tmp_path / "explained.json").read_text())
            assert explanation["decisions"], "newest trace carries decisions"
            assert any(d["layer"] == "plan" for d in explanation["decisions"])
        finally:
            await server.close()

    asyncio.run(go())
    assert "planned via" in capsys.readouterr().out

    # No server behind the URL: a clean JSON error, not a traceback.
    assert main(["explain", "t-1", "--url", "http://127.0.0.1:1"]) == 1
    assert "error" in json.loads(capsys.readouterr().out.splitlines()[-1])
