"""Executor semantics: concurrency, retries, ordered fallbacks, partial
failure — proving reference bugs B2-B5 are fixed (SURVEY.md §2.5)."""

import asyncio
import time

from mcpx.core.config import OrchestratorConfig
from mcpx.core.dag import DagEdge, DagNode, Plan
from mcpx.orchestrator.executor import Orchestrator

from tests.helpers import FakeService, make_transport


def run(coro):
    return asyncio.run(coro)


def orch(transport, **kw):
    cfg = OrchestratorConfig(retry_backoff_s=0.0)
    return Orchestrator(transport, cfg, **kw)


def test_linear_chain_wires_inputs():
    a = FakeService("a", result={"doc": "D"})
    b = FakeService("b")
    t = make_transport(a, b)
    plan = Plan(
        nodes=[
            DagNode(name="a", endpoint="local://a", inputs={"q": "query"}),
            DagNode(name="b", endpoint="local://b", inputs={"doc": "a"}),
        ],
        edges=[DagEdge("a", "b")],
    )
    res = run(orch(t).execute(plan, {"query": "hello"}))
    assert res.status == "ok"
    assert a.calls == [{"q": "hello"}]
    # b's 'doc' input resolves from a's *result* (results-before-payload,
    # reference control_plane.py:107 semantics).
    assert b.calls == [{"doc": {"doc": "D"}}]
    assert res.errors == {}


def test_generation_concurrency():
    # Two independent 60ms nodes must run concurrently (<100ms total), not
    # serially (>=120ms) — the reference walks serially (control_plane.py:104).
    l, r = FakeService("l"), FakeService("r")
    t = make_transport(l, r, latencies={"l": 0.06, "r": 0.06})
    plan = Plan(
        nodes=[
            DagNode(name="l", endpoint="local://l"),
            DagNode(name="r", endpoint="local://r"),
        ]
    )
    t0 = time.monotonic()
    res = run(orch(t).execute(plan, {}))
    elapsed = time.monotonic() - t0
    assert res.status == "ok"
    assert elapsed < 0.11, f"parallel generation took {elapsed:.3f}s (serial?)"


def test_retry_budget_recovers():
    flaky = FakeService("flaky", fail_times=2)
    t = make_transport(flaky)
    plan = Plan(nodes=[DagNode(name="flaky", endpoint="local://flaky", retries=2)])
    res = run(orch(t).execute(plan, {}))
    assert res.status == "ok"
    assert len(flaky.calls) == 3
    nt = res.trace.nodes["flaky"]
    assert [a.kind for a in nt.attempts] == ["primary", "retry", "retry"]
    assert nt.status == "ok"
    # B4 fixed: no stale error after recovery.
    assert res.errors == {}


def test_ordered_fallbacks():
    primary = FakeService("p", always_fail=True)
    fb1 = FakeService("fb1", always_fail=True)
    fb2 = FakeService("fb2", result={"ok": True})
    t = make_transport(primary, fb1, fb2)
    plan = Plan(
        nodes=[
            DagNode(
                name="n",
                endpoint="local://p",
                retries=0,
                fallbacks=["local://fb1", "local://fb2"],
            )
        ]
    )
    res = run(orch(t).execute(plan, {}))
    assert res.status == "ok"
    assert res.results["n"] == {"ok": True}
    kinds = [a.kind for a in res.trace.nodes["n"].attempts]
    assert kinds == ["primary", "fallback", "fallback"]


def test_partial_failure_keeps_results_and_skips_dependents():
    # B5 fixed: root branch failure doesn't discard the sibling branch.
    good = FakeService("good", result={"v": 1})
    bad = FakeService("bad", always_fail=True)
    down = FakeService("down")
    t = make_transport(good, bad, down)
    plan = Plan(
        nodes=[
            DagNode(name="good", endpoint="local://good"),
            DagNode(name="bad", endpoint="local://bad", retries=0),
            DagNode(name="down", endpoint="local://down", inputs={"x": "bad"}),
        ],
        edges=[DagEdge("bad", "down")],
    )
    res = run(orch(t).execute(plan, {}))
    assert res.status == "partial"
    assert res.results["good"] == {"v": 1}
    assert "bad" in res.errors
    assert res.errors["down"].startswith("skipped:")
    assert down.calls == []  # never invoked
    assert res.trace.nodes["down"].status == "skipped"


def test_all_failed_status():
    bad = FakeService("bad", always_fail=True)
    t = make_transport(bad)
    plan = Plan(nodes=[DagNode(name="bad", endpoint="local://bad", retries=0)])
    res = run(orch(t).execute(plan, {}))
    assert res.status == "failed"
    assert res.results == {}


def test_registry_resolves_endpoint_and_fallbacks():
    from mcpx.registry import InMemoryRegistry, ServiceRecord

    svc = FakeService("svc", always_fail=True)
    fb = FakeService("svc-fb", result={"via": "fallback"})
    t = make_transport(svc, fb)

    async def go():
        reg = InMemoryRegistry()
        await reg.put(
            ServiceRecord(
                name="svc", endpoint="local://svc", fallbacks=["local://svc-fb"]
            )
        )
        plan = Plan(nodes=[DagNode(name="svc", retries=0)])  # no endpoint in plan
        return await orch(t, registry=reg).execute(plan, {})

    res = run(go())
    assert res.status == "ok"
    assert res.results["svc"] == {"via": "fallback"}


def test_timeout_is_an_error():
    slow = FakeService("slow")
    t = make_transport(slow, latencies={"slow": 0.2})
    plan = Plan(nodes=[DagNode(name="slow", endpoint="local://slow", retries=0, timeout_s=0.05)])
    res = run(orch(t).execute(plan, {}))
    assert res.status == "failed"
    assert res.trace.nodes["slow"].attempts[0].status == "timeout"


def test_telemetry_recorded():
    from mcpx.telemetry.stats import TelemetryStore

    good = FakeService("good")
    t = make_transport(good)
    ts = TelemetryStore()
    plan = Plan(nodes=[DagNode(name="good", endpoint="local://good")])
    run(orch(t, telemetry=ts).execute(plan, {}))
    assert ts.get("good").calls == 1
