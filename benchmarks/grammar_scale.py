"""Grammar build cost vs registry scale (VERDICT r4 weak #5).

The sparse DFA×trie product's 30M-visit budget bounds build cost by
*assumption*; this probe bounds it by *measurement*: for registry sizes
1k→100k it times the constrained-grammar build on each committed vocab,
reports the compact-table footprint, and records which fallback tier the
planner's ladder (keys→no-keys→shape-only) would actually land on — the
registry-name guarantee is only as real as the tier that compiles.

Host-only (grammar construction never touches the device); one JSON line
per (vocab, size) so the ladder table in BASELINE.md is a paste of stdout.

Usage: [SIZES=1000,10000] python benchmarks/grammar_scale.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mcpx.models.tokenizer import make_tokenizer  # noqa: E402
from mcpx.planner.grammar import build_plan_grammar  # noqa: E402
from mcpx.utils.synth import synth_registry  # noqa: E402


def _table_mb(g) -> float:
    total = 0
    for name in ("ctrans", "cmask", "active_ids", "eos_cols"):
        arr = getattr(g, name, None)
        if arr is not None:
            total += arr.size * arr.itemsize
    return total / 1e6


def probe(vocab: str, n: int) -> dict:
    tok = make_tokenizer(vocab)
    records = synth_registry(n, seed=0)
    names = [r.name for r in records]
    keys = sorted(
        {k for r in records for k in (*r.input_schema, *r.output_schema)}
    )
    out: dict = {"vocab": vocab, "n_services": n, "n_keys": len(keys)}
    # The planner's fallback ladder, timed tier by tier.
    for tier, kw in (
        ("keys", dict(service_names=names, input_keys=keys)),
        ("names_only", dict(service_names=names)),
        ("shape_only", dict()),
    ):
        t0 = time.perf_counter()
        try:
            g = build_plan_grammar(tok, **kw)
            out[tier] = {
                "build_s": round(time.perf_counter() - t0, 3),
                "n_states": int(g.ctrans.shape[0]),
                "n_cols": int(g.ctrans.shape[1]),
                "table_mb": round(_table_mb(g), 2),
            }
            if "tier" not in out:
                out["tier"] = tier  # what the planner would serve with
        except ValueError as e:
            out[tier] = {"build_s": round(time.perf_counter() - t0, 3),
                         "error": str(e)[:100]}
    return out


def main() -> None:
    sizes = [int(s) for s in os.environ.get(
        "SIZES", "1000,3000,10000,30000,100000").split(",")]
    for vocab in ("byte", "bpe"):
        for n in sizes:
            print(json.dumps(probe(vocab, n)), flush=True)


if __name__ == "__main__":
    main()
