#!/bin/bash
# Poll the axon tunnel on a 5-minute cadence; on the first ALIVE probe run
# one full TPU session (benchmarks/tpu_session.sh), then keep polling —
# the relay has recovered hours after a wedge before (r3->r4), so a failed
# session is not a reason to stop. The loop exits only once the headline
# artifact (benchmarks/bench_tpu.json) carries a non-CPU backend, i.e. a
# real TPU number has landed.
cd "$(dirname "$0")/.."
LOG=benchmarks/tunnel_probe_r5.log
while true; do
  ts=$(date -u +%FT%T)
  if python benchmarks/tunnel_probe.py 75 > /dev/null 2>&1; then
    echo "$ts ALIVE -> launching tpu_session" >> "$LOG"
    # mtime nonce: keep_if_json deliberately preserves a prior session's
    # good artifact across a failed session, so "the file says 2b/tpu" is
    # not evidence THIS session measured anything — require the artifact to
    # have actually been rewritten since the session started.
    before=$(stat -c %Y benchmarks/bench_tpu.json 2>/dev/null || echo 0)
    bash benchmarks/tpu_session.sh >> benchmarks/tpu_session_r5.log 2>&1
    echo "$(date -u +%FT%T) session-done" >> "$LOG"
    after=$(stat -c %Y benchmarks/bench_tpu.json 2>/dev/null || echo 0)
    if [ "$after" != "$before" ] && python - <<'EOF'
import json, sys
try:
    d = json.load(open("benchmarks/bench_tpu.json"))
except Exception:
    sys.exit(1)
# Only a 2b TPU number ends the hunt: a model=test demotion means the smoke
# ladder (which now includes the no-Pallas tier) should get another window.
sys.exit(0 if d.get("backend") not in (None, "cpu") and d.get("model") == "2b" else 1)
EOF
    then
      echo "$(date -u +%FT%T) tpu-number-landed; loop exiting" >> "$LOG"
      exit 0
    fi
  else
    echo "$ts no-listener" >> "$LOG"
  fi
  sleep 300
done
