#!/usr/bin/env python
"""Direct-engine probe: drive InferenceEngine with concurrent constrained
requests (no HTTP server, no retrieval) and print occupancy/cohort stats —
the tool for attributing serving throughput between the engine proper and
the control-plane layers above it."""

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if int(os.environ.get("PROBE_CPU", "0")) > 0:
    # env vars alone cannot override the axon sitecustomize's latched TPU
    # backend — and the TPU tunnel admits ONE client (a second process
    # BLOCKS in make_c_api_client, not errors). Virtual CPU must be armed
    # through the shared recipe.
    from __graft_entry__ import _force_virtual_cpu

    _force_virtual_cpu(int(os.environ["PROBE_CPU"]))


async def main():
    from mcpx.core.config import MCPXConfig
    from mcpx.engine.engine import InferenceEngine
    from mcpx.planner.grammar import build_plan_grammar

    n_req = int(os.environ.get("PROBE_REQUESTS", "256"))
    cfg = MCPXConfig.from_dict(
        {
            "model": {"size": os.environ.get("PROBE_MODEL", "2b"), "max_seq_len": 2048},
            "engine": {
                "max_batch_size": int(os.environ.get("PROBE_BATCH", "64")),
                "max_decode_len": 96,
                "kv_page_size": 64,
                "max_pages_per_seq": 16,
                "temperature": 0.0,
                "use_pallas": True,
                # The explicit warm round below compiles exactly the buckets
                # the probe exercises; full warmup would compile all of them.
                "warmup_compile": False,
                "decode_steps_per_tick": int(os.environ.get("PROBE_TICK", "2")),
                "speculate_k": int(os.environ.get("PROBE_SPEC", "8")),
            },
        }
    )
    import jax
    if jax.default_backend() == "cpu":
        cfg.engine.use_pallas = False
    eng = InferenceEngine(cfg)
    t0 = time.monotonic()
    await eng.start()
    t_start = time.monotonic() - t0

    names = [f"svc-{kind}-{i:04d}" for kind in ("fetch", "rank", "notify", "merge") for i in range(250)]
    keys = ["query", "user_id", "order_id", "document", "text", "items", "amount",
            "address", "score", "status", "report", "features", "vector", "summary"]
    with_keys = os.environ.get("PROBE_KEYS", "1") == "1"
    grammar = build_plan_grammar(eng.tokenizer, names, input_keys=keys if with_keys else None)
    prompt = ("Compose a service DAG. JSON\nServices:\n"
              + "\n".join(f"{n} in:a,b out:c" for n in names[:6])
              + "\nIntent: fetch and rank the things\nJSON:")
    ids = eng.tokenizer.encode(prompt)

    # Warm every admission-cohort bucket the timed phase could hit, so no
    # XLA compile lands inside the measured window (warmup_compile is off —
    # it would also compile prompt buckets this probe never uses).
    for a in eng._batch_buckets:
        await asyncio.gather(*(eng.generate(ids, max_new_tokens=96, grammar=grammar)
                               for _ in range(a)))
    m0 = {k: c._value.get() for k, c in
          [("fwd", eng.metrics.decode_forwards), ("tok", eng.metrics.decode_tokens),
           ("adm", eng.metrics.admissions), ("rows", eng.metrics.admitted_rows),
           ("segrows", eng.metrics.segment_active_rows), ("seg", eng.metrics.segments),
           ("pft", eng.metrics.prefill_tokens)]}
    t1 = time.monotonic()
    results = await asyncio.gather(*(eng.generate(ids, max_new_tokens=96, grammar=grammar)
                                     for _ in range(n_req)))
    dt = time.monotonic() - t1
    m1 = {k: c._value.get() for k, c in
          [("fwd", eng.metrics.decode_forwards), ("tok", eng.metrics.decode_tokens),
           ("adm", eng.metrics.admissions), ("rows", eng.metrics.admitted_rows),
           ("segrows", eng.metrics.segment_active_rows), ("seg", eng.metrics.segments),
           ("pft", eng.metrics.prefill_tokens)]}
    d = {k: m1[k] - m0[k] for k in m0}
    gen = sum(r.generated_tokens for r in results)
    print(json.dumps({
        "plans_per_sec": round(n_req / dt, 2),
        "elapsed_s": round(dt, 2),
        "startup_s": round(t_start, 1),
        "gen_tokens": gen,
        "decode_forwards": int(d["fwd"]),
        "tok_per_forward": round(d["tok"] / max(1, d["fwd"]), 1),
        "avg_cohort": round(d["rows"] / max(1, d["adm"]), 1),
        "admissions": int(d["adm"]),
        "avg_occupancy": round(d["segrows"] / max(1, d["seg"]), 1),
        "segments": int(d["seg"]),
        "prefill_tokens": int(d["pft"]),
        "prompt_len": len(ids),
        "p50_decode_ms": round(sorted(r.decode_ms for r in results)[n_req // 2], 1),
        "p50_prefill_ms": round(sorted(r.prefill_ms for r in results)[n_req // 2], 1),
        "p50_queue_ms": round(sorted(r.queue_ms for r in results)[n_req // 2], 1),
    }))
    await eng.aclose()


if __name__ == "__main__":
    asyncio.run(main())
