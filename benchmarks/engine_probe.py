#!/usr/bin/env python
"""Direct-engine probe: drive InferenceEngine with concurrent constrained
requests (no HTTP server, no retrieval) and print occupancy/cohort stats —
the tool for attributing serving throughput between the engine proper and
the control-plane layers above it.

Env knobs: PROBE_MODEL (2b|test), PROBE_REQUESTS, PROBE_BATCH, PROBE_TICK,
PROBE_SPEC, PROBE_DEPTH (worker pipeline depth), PROBE_KEYS (1 = trie the
"in" keys), PROBE_CPU=N (arm an
N-device virtual CPU platform — env vars alone cannot evict the latched TPU
backend, and the tunnel blocks a second client in make_c_api_client).

PROBE_SWEEP runs several configs in ONE process — one tunnel session (the
expensive part on this dev box: a second process blocks on the relay), with
XLA compiles shared through the persistent compilation cache; each entry
still builds a fresh engine (weights re-init + trace per config):

    PROBE_SWEEP="tick=2;tick=8;batch=128,tick=2;spec=16" python benchmarks/engine_probe.py

Each ';'-separated entry is a comma list of overrides (tick, spec, batch,
keys, requests); unset fields fall back to the env/default values.
"""

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _pallas_on, _serving_announced

if int(os.environ.get("PROBE_CPU", "0")) > 0:
    from __graft_entry__ import _force_virtual_cpu

    _force_virtual_cpu(int(os.environ["PROBE_CPU"]))


_COUNTERS = (
    ("fwd", "decode_forwards"),
    ("tok", "decode_tokens"),
    ("adm", "admissions"),
    ("rows", "admitted_rows"),
    ("segrows", "segment_active_rows"),
    ("seg", "segments"),
    ("pft", "prefill_tokens"),
)


def _snap(eng):
    return {k: getattr(eng.metrics, attr)._value.get() for k, attr in _COUNTERS}


async def run_one(*, model: str, n_req: int, batch: int, tick: int, spec: int,
                  with_keys: bool, depth: int, vocab: str, minfree: int,
                  wait: float, budget: int, draft: str = "prompt") -> dict:
    from mcpx.core.config import MCPXConfig
    from mcpx.engine.engine import InferenceEngine
    from mcpx.planner.grammar import build_plan_grammar

    cfg = MCPXConfig.from_dict(
        {
            "model": {"size": model, "max_seq_len": 2048, "vocab": vocab},
            "engine": {
                "max_batch_size": batch,
                "max_decode_len": budget,
                # SAME KV geometry as bench.py's BPE config: the r5 sweep
                # died when the relay dropped during its first entry's
                # compile burst — pages=16 made every (batch, len) bucket a
                # fresh executable instead of a persistent-cache hit from
                # the headline run. 4 x 64-token pages hold the probe's
                # 128-token prompt + up to a 96-token budget + spec slack.
                "kv_page_size": 64,
                "max_pages_per_seq": 4,
                "temperature": 0.0,
                # One definition of the session-wide Pallas gate (tpu AND
                # MCPX_BENCH_PALLAS != "0"); the cpu-backend clear below
                # stays for PROBE_CPU virtual-device runs.
                "use_pallas": _pallas_on(),
                # The explicit warm rounds below compile exactly the buckets
                # the probe exercises; full warmup would compile all of them.
                "warmup_compile": False,
                "decode_steps_per_tick": tick,
                "speculate_k": spec,
                "pipeline_depth": depth,
                "admit_min_free": minfree,
                "admit_max_wait_s": wait,
                "draft_mode": draft,
            },
        }
    )
    import jax

    if jax.default_backend() == "cpu":
        cfg.engine.use_pallas = False
    _serving_announced(batch, "probe config", tag="probe")
    eng = InferenceEngine(cfg)
    t0 = time.monotonic()
    await eng.start()
    t_start = time.monotonic() - t0

    names = [f"svc-{kind}-{i:04d}" for kind in ("fetch", "rank", "notify", "merge")
             for i in range(250)]
    keys = ["query", "user_id", "order_id", "document", "text", "items", "amount",
            "address", "score", "status", "report", "features", "vector", "summary"]
    grammar = build_plan_grammar(eng.tokenizer, names,
                                 input_keys=keys if with_keys else None)
    prompt = ("Compose a service DAG. JSON\nServices:\n"
              + "\n".join(f"{n} in:a,b out:c" for n in names[:6])
              + "\nIntent: fetch and rank the things\nJSON:")
    ids = eng.tokenizer.encode(prompt)

    # Warm every admission-cohort bucket the timed phase could hit, so no
    # XLA compile lands inside the measured window.
    for a in eng._batch_buckets:
        await asyncio.gather(*(eng.generate(ids, max_new_tokens=budget, grammar=grammar)
                               for _ in range(a)))
    m0 = _snap(eng)
    t1 = time.monotonic()
    results = await asyncio.gather(*(eng.generate(ids, max_new_tokens=budget, grammar=grammar)
                                     for _ in range(n_req)))
    dt = time.monotonic() - t1
    m1 = _snap(eng)
    d = {k: m1[k] - m0[k] for k in m0}
    gen = sum(r.generated_tokens for r in results)
    out = {
        "model": model, "batch": batch, "tick": tick, "spec": spec,
        "depth": depth, "vocab": vocab, "minfree": minfree, "wait": wait,
        "budget": budget, "draft": draft,
        "keys": int(with_keys), "requests": n_req,
        "plans_per_sec": round(n_req / dt, 2),
        "elapsed_s": round(dt, 2),
        "startup_s": round(t_start, 1),
        "gen_tokens": gen,
        "decode_forwards": int(d["fwd"]),
        "tok_per_forward": round(d["tok"] / max(1, d["fwd"]), 1),
        "avg_cohort": round(d["rows"] / max(1, d["adm"]), 1),
        "admissions": int(d["adm"]),
        "avg_occupancy": round(d["segrows"] / max(1, d["seg"]), 1),
        "segments": int(d["seg"]),
        "prefill_tokens": int(d["pft"]),
        "prompt_len": len(ids),
        "p50_decode_ms": round(sorted(r.decode_ms for r in results)[n_req // 2], 1),
        "p50_prefill_ms": round(sorted(r.prefill_ms for r in results)[n_req // 2], 1),
        "p50_queue_ms": round(sorted(r.queue_ms for r in results)[n_req // 2], 1),
    }
    await eng.aclose()
    return out


def _base() -> dict:
    return {
        "model": os.environ.get("PROBE_MODEL", "2b"),
        "n_req": int(os.environ.get("PROBE_REQUESTS", "256")),
        "batch": int(os.environ.get("PROBE_BATCH", "64")),
        "tick": int(os.environ.get("PROBE_TICK", "2")),
        "spec": int(os.environ.get("PROBE_SPEC", "8")),
        "with_keys": os.environ.get("PROBE_KEYS", "1") == "1",
        "depth": int(os.environ.get("PROBE_DEPTH", "2")),
        "vocab": os.environ.get("PROBE_VOCAB", "bpe"),
        "minfree": int(os.environ.get("PROBE_MINFREE", "0")),
        "wait": float(os.environ.get("PROBE_WAIT", "0.15")),
        "budget": int(os.environ.get("PROBE_BUDGET", "96")),
        "draft": os.environ.get("PROBE_DRAFT", "prompt"),
    }


async def main() -> None:
    sweep = os.environ.get("PROBE_SWEEP", "")
    configs = []
    if sweep:
        for entry in filter(None, (e.strip() for e in sweep.split(";"))):
            c = _base()
            for kv in filter(None, entry.split(",")):
                k, _, v = kv.partition("=")
                k, v = k.strip(), v.strip()
                if k == "keys":
                    c["with_keys"] = v == "1"
                elif k == "requests":
                    c["n_req"] = int(v)
                elif k in ("tick", "spec", "batch", "depth", "minfree", "budget"):
                    c[k] = int(v)
                elif k == "wait":
                    c["wait"] = float(v)
                elif k == "model":
                    c["model"] = v
                elif k == "vocab":
                    c["vocab"] = v
                elif k == "draft":
                    c["draft"] = v
                else:
                    raise SystemExit(f"unknown sweep key {k!r}")
            configs.append(c)
    else:
        configs.append(_base())
    for c in configs:
        print(json.dumps(await run_one(**c)), flush=True)


if __name__ == "__main__":
    asyncio.run(main())
