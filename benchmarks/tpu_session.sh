#!/bin/bash
# One TPU tunnel session, headline first: the axon tunnel admits one client
# process at a time (a second blocks silently), so run everything in order
# from a single shell; each step is timeout-guarded, and artifacts are
# written to a temp path and moved only on non-empty output — a mid-session
# wedge never clobbers a previous session's good artifact.
#
#   1. bench.py            -> benchmarks/bench_tpu.json  (headline + quality)
#   2. ladder.py           -> benchmarks/ladder_tpu.json (5 BASELINE configs)
#   3. engine_probe sweeps -> benchmarks/probe_sweep_tpu.txt (p50 levers:
#      budget/tick/minfree/spec/depth — pick the p50-optimal into bench.py)
#
# Usage: bash benchmarks/tpu_session.sh
set -x
cd "$(dirname "$0")/.."

keep_if_nonempty() {  # $1 tmp, $2 dest
  if [ -s "$1" ]; then mv "$1" "$2"; else rm -f "$1"; fi
}

keep_if_json() {  # $1 tmp, $2 dest — only complete JSON may replace a good artifact
  if [ -s "$1" ] && python -m json.tool "$1" > /dev/null 2>&1; then
    mv "$1" "$2"
  else
    rm -f "$1"
  fi
}

# grep + json.tool so neither a non-JSON diagnostic nor a timeout-truncated
# fragment can replace a previous session's good artifact (ADVICE r4).
timeout 3000 python bench.py 2> >(tail -5 >&2) | grep -E '^\{' | tail -1 > benchmarks/.bench_tpu.tmp
keep_if_json benchmarks/.bench_tpu.tmp benchmarks/bench_tpu.json
cat benchmarks/bench_tpu.json 2>/dev/null

# r5 honesty/measurement rows (smaller request counts: each is one labelled
# row, not the headline): OOD registry (unfitted BPE compression), repeat-
# intent plan-cache lever, SP-vocab real-checkpoint serving configuration.
MCPX_BENCH_REGISTRY=ood MCPX_BENCH_REQUESTS=256 MCPX_BENCH_LATENCY_REQUESTS=96 MCPX_BENCH_SKIP_QUALITY=1 \
  timeout 1800 python bench.py 2> >(tail -3 >&2) | grep -E '^\{' | tail -1 > benchmarks/.bench_ood.tmp
keep_if_json benchmarks/.bench_ood.tmp benchmarks/bench_tpu_ood.json
cat benchmarks/bench_tpu_ood.json 2>/dev/null

MCPX_BENCH_UNIQUE_INTENTS=64 MCPX_BENCH_REQUESTS=512 MCPX_BENCH_LATENCY_REQUESTS=96 MCPX_BENCH_SKIP_QUALITY=1 \
  timeout 1800 python bench.py 2> >(tail -3 >&2) | grep -E '^\{' | tail -1 > benchmarks/.bench_cache.tmp
keep_if_json benchmarks/.bench_cache.tmp benchmarks/bench_tpu_cache.json
cat benchmarks/bench_tpu_cache.json 2>/dev/null

MCPX_BENCH_VOCAB=sp MCPX_BENCH_REQUESTS=256 MCPX_BENCH_LATENCY_REQUESTS=96 MCPX_BENCH_SKIP_QUALITY=1 \
  timeout 2400 python bench.py 2> >(tail -3 >&2) | grep -E '^\{' | tail -1 > benchmarks/.bench_sp.tmp
keep_if_json benchmarks/.bench_sp.tmp benchmarks/bench_tpu_sp.json
cat benchmarks/bench_tpu_sp.json 2>/dev/null

timeout 3000 python benchmarks/ladder.py 2> >(tail -5 >&2) > benchmarks/.ladder_tpu.tmp
keep_if_nonempty benchmarks/.ladder_tpu.tmp benchmarks/ladder_tpu.json
cat benchmarks/ladder_tpu.json 2>/dev/null

PROBE_SWEEP="budget=40;budget=32;budget=48;budget=40,tick=2;budget=40,minfree=1;budget=40,minfree=16;budget=40,spec=4;budget=40,depth=3;budget=40,draft=off;budget=40,tick=1;budget=40,tick=8" \
  timeout 3500 python benchmarks/engine_probe.py 2>&1 | grep -E '^\{' > benchmarks/.probe_sweep_tpu.tmp
keep_if_nonempty benchmarks/.probe_sweep_tpu.tmp benchmarks/probe_sweep_tpu.txt
cat benchmarks/probe_sweep_tpu.txt 2>/dev/null
