#!/bin/bash
# One TPU tunnel session, headline first: the axon tunnel admits one client
# process at a time (a second blocks silently), so run everything in order
# from a single shell. Usage: bash benchmarks/tpu_session.sh
set -x
cd "$(dirname "$0")/.."
python bench.py 2>&1 | tail -3
PROBE_SWEEP="budget=40;budget=32;budget=48;budget=40,tick=2;budget=40,minfree=1;budget=40,minfree=16;budget=40,spec=4;budget=40,depth=3" \
  timeout 3500 python benchmarks/engine_probe.py 2>&1 | grep -E '^\{'
