#!/bin/bash
# One TPU tunnel session, cheapest-first: the axon tunnel admits one client
# process at a time (a second blocks silently), so run everything in order
# from a single shell; each step is timeout-guarded, full stderr goes to
# per-step logs under benchmarks/logs/ (r5: the 2b startup failure was
# unobservable through the old `tail -5` stderr filter), and artifacts are
# written to a temp path and moved only on valid JSON — a mid-session wedge
# never clobbers a previous session's good artifact.
#
#   0. startup_smoke.py    -> benchmarks/smoke_tpu.json   (2b bring-up at
#      batch 64 then 32; exports MCPX_BENCH_BATCH for the bench steps;
#      a bring-up that kills the tunnel costs its own step here, not the
#      whole session)
#   1. bench.py            -> benchmarks/bench_tpu.json  (headline + quality)
#   2. honesty rows        -> bench_tpu_{ood,cache,sp}.json
#   3. ladder.py           -> benchmarks/ladder_tpu.json (5 BASELINE configs)
#   4. engine_probe sweeps -> benchmarks/probe_sweep_tpu.txt (p50 levers)
#
# Usage: bash benchmarks/tpu_session.sh
set -x
cd "$(dirname "$0")/.."
mkdir -p benchmarks/logs

keep_if_nonempty() {  # $1 tmp, $2 dest
  if [ -s "$1" ]; then mv "$1" "$2"; else rm -f "$1"; fi
}

keep_if_json() {  # $1 tmp, $2 dest — only complete JSON may replace a good artifact
  if [ -s "$1" ] && python -m json.tool "$1" > /dev/null 2>&1; then
    mv "$1" "$2"
  else
    rm -f "$1"
  fi
}

# ---- 0. 2b bring-up smoke: find the batch size that serves (or fail fast
# with a full traceback in the log instead of burning the headline step).
# Gating reads THIS session's output (.smoke_out), never the published
# artifact — keep_if_json intentionally preserves a previous session's
# smoke_tpu.json when this one produces nothing, and a stale "ok" must not
# steer this session's steps.
# Budget covers THREE worst-case wedged attempts (64, 32, 32np at the
# ~2100s child cap each) + floor slack: the 32np Mosaic-attribution tier
# matters most precisely when the earlier attempts wedge, so it must not
# be the one the budget starves. Outer timeout stays clear of the driver's
# own deadline so it never SIGTERMs mid-attempt.
export MCPX_SMOKE_TOTAL_S="${MCPX_SMOKE_TOTAL_S:-6300}"
# Outer timeout DERIVED from the driver's budget: an operator-raised
# MCPX_SMOKE_TOTAL_S must not re-create the mid-attempt SIGTERM hazard a
# hardcoded cap would reintroduce.
timeout "$((${MCPX_SMOKE_TOTAL_S%.*} + 300))" python benchmarks/startup_smoke.py \
  2> benchmarks/logs/smoke.err | grep -E '^\{' | tail -1 > benchmarks/.smoke_out
cp benchmarks/.smoke_out benchmarks/.smoke_tpu.tmp
keep_if_json benchmarks/.smoke_tpu.tmp benchmarks/smoke_tpu.json
cat benchmarks/.smoke_out
SMOKE_BATCH=$(python - <<'EOF' 2>/dev/null
import json
try:
    d = json.load(open("benchmarks/.smoke_out"))
    print(d["batch"] if d.get("ok") else "")
except Exception:
    print("")
EOF
)
SMOKE_PALLAS=$(python - <<'EOF' 2>/dev/null
import json
try:
    d = json.load(open("benchmarks/.smoke_out"))
    print("" if (not d.get("ok")) or d.get("pallas", True) else "0")
except Exception:
    print("")
EOF
)
rm -f benchmarks/.smoke_out
if [ -n "$SMOKE_BATCH" ]; then
  export MCPX_BENCH_BATCH="$SMOKE_BATCH"
  # The probe sweep builds its own engines: give it the proven batch too.
  export PROBE_BATCH="$SMOKE_BATCH"
  if [ "$SMOKE_PALLAS" = "0" ]; then
    # The smoke only served with the Pallas kernel off (Mosaic hypothesis
    # confirmed): every downstream step must serve the same fused-jnp path.
    export MCPX_BENCH_PALLAS=0
  else
    # Pin the other way too: a stale =0 inherited from the launching shell
    # (e.g. a prior Mosaic-debug run) must not flip the downstream steps to
    # fused-jnp while smoke_tpu.json records the Pallas kernel as proven.
    export MCPX_BENCH_PALLAS=1
  fi
else
  # 2b proved unservable (or the smoke never completed): a measured
  # model=test TPU number beats four steps of re-failing 2b bring-up.
  export MCPX_BENCH_MODEL=test
  # engine_probe selects via PROBE_MODEL (default 2b), not MCPX_BENCH_MODEL
  # — without this the sweep step would re-fail the exact bring-up the
  # smoke fenced off.
  export PROBE_MODEL=test
fi

# Quality rows are backend-independent (CPU-pinned evals, measured every
# round); bound them well inside this step's timeout so a wedged quality
# phase can never burn the step budget and discard the measured THROUGHPUT
# headline — the one number only a TPU session can produce.
MCPX_BENCH_QUALITY_TIMEOUT_S=900 \
  timeout 3000 python bench.py 2> benchmarks/logs/bench.err | grep -E '^\{' | tail -1 > benchmarks/.bench_tpu.tmp
tail -5 benchmarks/logs/bench.err >&2
keep_if_json benchmarks/.bench_tpu.tmp benchmarks/bench_tpu.json
cat benchmarks/bench_tpu.json 2>/dev/null

# r5 honesty/measurement rows (smaller request counts: each is one labelled
# row, not the headline): OOD registry (unfitted BPE compression), repeat-
# intent plan-cache lever, SP-vocab real-checkpoint serving configuration.
MCPX_BENCH_REGISTRY=ood MCPX_BENCH_REQUESTS=256 MCPX_BENCH_LATENCY_REQUESTS=96 MCPX_BENCH_SKIP_QUALITY=1 \
  timeout 1800 python bench.py 2> benchmarks/logs/bench_ood.err | grep -E '^\{' | tail -1 > benchmarks/.bench_ood.tmp
keep_if_json benchmarks/.bench_ood.tmp benchmarks/bench_tpu_ood.json
cat benchmarks/bench_tpu_ood.json 2>/dev/null

MCPX_BENCH_UNIQUE_INTENTS=64 MCPX_BENCH_REQUESTS=512 MCPX_BENCH_LATENCY_REQUESTS=96 MCPX_BENCH_SKIP_QUALITY=1 \
  timeout 1800 python bench.py 2> benchmarks/logs/bench_cache.err | grep -E '^\{' | tail -1 > benchmarks/.bench_cache.tmp
keep_if_json benchmarks/.bench_cache.tmp benchmarks/bench_tpu_cache.json
cat benchmarks/bench_tpu_cache.json 2>/dev/null

MCPX_BENCH_VOCAB=sp MCPX_BENCH_REQUESTS=256 MCPX_BENCH_LATENCY_REQUESTS=96 MCPX_BENCH_SKIP_QUALITY=1 \
  timeout 2400 python bench.py 2> benchmarks/logs/bench_sp.err | grep -E '^\{' | tail -1 > benchmarks/.bench_sp.tmp
keep_if_json benchmarks/.bench_sp.tmp benchmarks/bench_tpu_sp.json
cat benchmarks/bench_tpu_sp.json 2>/dev/null

# Weight-only int8 row (models/gemma/quant.py): halves the decode
# weight-streaming bill — on a weight-load-bound decode this is the
# direct lever — and halves params-at-rest (2B: ~5 GB -> ~2.6 GB),
# which may be exactly the headroom the batch-64 wedge was missing.
MCPX_BENCH_QUANTIZE=int8 MCPX_BENCH_REQUESTS=256 MCPX_BENCH_LATENCY_REQUESTS=96 MCPX_BENCH_SKIP_QUALITY=1 \
  timeout 1800 python bench.py 2> benchmarks/logs/bench_int8.err | grep -E '^\{' | tail -1 > benchmarks/.bench_int8.tmp
keep_if_json benchmarks/.bench_int8.tmp benchmarks/bench_tpu_int8.json
cat benchmarks/bench_tpu_int8.json 2>/dev/null

# Latency-profile row (VERDICT r4 next #2): admission tuned for p50 —
# small cohort hysteresis off (minfree=1), short admit wait, tick 2 so
# retirement/admission cadence tightens — at a gentler offered load
# (0.5x measured throughput). Throughput cost is expected and visible in
# the same row; the open-loop p50 + phase_p50_open_ms decomposition is
# the point.
MCPX_BENCH_TICK=2 MCPX_BENCH_WAIT=0.02 MCPX_BENCH_MINFREE=1 MCPX_BENCH_RATE_FRACTION=0.5 \
  MCPX_BENCH_REQUESTS=256 MCPX_BENCH_LATENCY_REQUESTS=128 MCPX_BENCH_SKIP_QUALITY=1 \
  timeout 1800 python bench.py 2> benchmarks/logs/bench_latency.err | grep -E '^\{' | tail -1 > benchmarks/.bench_latency.tmp
keep_if_json benchmarks/.bench_latency.tmp benchmarks/bench_tpu_latency.json
cat benchmarks/bench_tpu_latency.json 2>/dev/null

timeout 3000 python benchmarks/ladder.py 2> benchmarks/logs/ladder.err > benchmarks/.ladder_tpu.tmp
keep_if_nonempty benchmarks/.ladder_tpu.tmp benchmarks/ladder_tpu.json
cat benchmarks/ladder_tpu.json 2>/dev/null

# Trimmed to the p50/throughput levers that matter after the r5 headline
# (each entry is a fresh engine bring-up; window longevity is the scarce
# resource — the r5 sweep died with zero entries at 11).
PROBE_SWEEP="budget=40;budget=40,tick=2;budget=40,tick=1;budget=40,minfree=1;budget=40,minfree=16;budget=40,depth=3;budget=40,draft=off" \
  timeout 3500 python benchmarks/engine_probe.py 2> benchmarks/logs/probe.err | grep -E '^\{' > benchmarks/.probe_sweep_tpu.tmp
keep_if_nonempty benchmarks/.probe_sweep_tpu.tmp benchmarks/probe_sweep_tpu.txt
cat benchmarks/probe_sweep_tpu.txt 2>/dev/null
