#!/usr/bin/env python
"""2B engine bring-up smoke: the cheapest possible TPU-session first move.

The r5 headline attempt burned its whole 50-minute step on `model=2b`
engine startup that died with an unobserved RuntimeError (and took the
axon tunnel down with it — relay gone, same signature as the r3 device
OOM). This script isolates exactly that bring-up so a fresh tunnel window
spends minutes, not the session, finding out whether 2B serves.

Two modes:

  --single BATCH   (child) one bring-up attempt at that batch in THIS
                   process: build bench's exact 2B config, engine.start()
                   under a watchdog (MCPX_SMOKE_TIMEOUT_S, default 900),
                   one constrained generate through the registry grammar,
                   aclose(); print one JSON line; exit 0 on success.

  (no args)        (driver) run `--single B` for each spec B in
                   MCPX_SMOKE_BATCHES (default "64,32,32np"; "np" = Pallas
                   kernel off, serving the fused-jnp attention) as a
                   SUBPROCESS —
                   a failed or wedged attempt's HBM (and any stuck worker
                   thread) dies with its process instead of poisoning the
                   next attempt with RESOURCE_EXHAUSTED it didn't earn.
                   The driver itself never imports jax, so it holds no
                   tunnel client. First success wins; its JSON is echoed.

Exit 0 iff some batch served. The session script keys on the printed
batch to set MCPX_BENCH_BATCH for the real bench run, and falls back to
MCPX_BENCH_MODEL=test when no batch serves.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_spec(spec: str) -> tuple[int, bool]:
    """"64" -> (64, pallas on); "32np" -> (32, pallas off). The np tier
    exists because the r5 startup RuntimeError is unattributed between HBM
    pressure (batch-dependent) and the first-ever hardware Mosaic compile
    of the paged-attention kernel (batch-independent) — a ladder over
    batches alone cannot distinguish them."""
    if spec.endswith("np"):
        return int(spec[:-2]), False
    return int(spec), True


def run_single(spec: str) -> int:
    import asyncio
    import faulthandler
    import traceback

    faulthandler.dump_traceback_later(
        float(os.environ.get("MCPX_SMOKE_HANG_DUMP_S", "1100")), exit=False
    )
    timeout_s = float(os.environ.get("MCPX_SMOKE_TIMEOUT_S", "900"))
    batch, pallas = _parse_spec(spec)
    os.environ["MCPX_BENCH_BATCH"] = str(batch)
    # Pin explicitly BOTH ways: an inherited MCPX_BENCH_PALLAS=0 from the
    # operator's shell must not make a pallas-on spec silently serve the
    # fused-jnp path while reporting "pallas": true.
    os.environ["MCPX_BENCH_PALLAS"] = "1" if pallas else "0"

    async def go() -> dict | None:
        from bench import _build_config
        from mcpx.engine.engine import InferenceEngine
        from mcpx.planner.grammar import build_plan_grammar
        from mcpx.utils.synth import synth_registry

        cfg = _build_config("2b")
        eng = InferenceEngine(cfg)
        t0 = time.monotonic()
        try:
            await asyncio.wait_for(eng.start(), timeout=timeout_s)
            t_start = time.monotonic() - t0
            records = synth_registry(1000, seed=0)
            grammar = build_plan_grammar(
                eng.tokenizer,
                [r.name for r in records],
                input_keys=sorted(
                    {k for r in records for k in (*r.input_schema, *r.output_schema)}
                ),
            )
            prompt = eng.tokenizer.encode(
                "Compose a service DAG.\nIntent: fetch auth\nJSON:"
            )
            t1 = time.monotonic()
            # First-plan budget: the first constrained generate pays the
            # registry grammar's device-table upload (~125 MB of BPE trie
            # tables at 1k services, minutes over the ~1 MB/s tunnel) plus
            # the grammar-state-bucket executable compiles at the REAL
            # batch size — measured 124 s at batch 32 (07:44 session). The
            # old 300 s cap read "slow first plan at batch 64" as "batch 64
            # failed", demoting sessions to half the proven throughput tier.
            res = await asyncio.wait_for(
                eng.generate(prompt, constrained=True, grammar=grammar),
                timeout=float(os.environ.get("MCPX_SMOKE_PLAN_TIMEOUT_S", "720")),
            )
            return {
                "ok": True,
                "batch": batch,
                "pallas": pallas,
                "startup_s": round(t_start, 1),
                "first_plan_s": round(time.monotonic() - t1, 1),
                "text_head": res.text[:60],
            }
        except Exception:
            traceback.print_exc()
            return None
        # KeyboardInterrupt/SystemExit propagate: an operator abort must
        # abort, not read as "this batch failed". No aclose() on the way
        # out — the process exit releases HBM more reliably than a
        # cooperative close whose worker may be the thing that's stuck.

    out = asyncio.run(go())
    if out is None:
        return 1
    print(json.dumps(out), flush=True)
    return 0


def main() -> int:
    if len(sys.argv) == 3 and sys.argv[1] == "--single":
        return run_single(sys.argv[2])
    timeout_s = float(os.environ.get("MCPX_SMOKE_TIMEOUT_S", "900"))
    # The driver owns the TOTAL budget (default 6300s: THREE full worst-case
    # attempts at the ~2100s child cap — the default ladder is three tiers,
    # and the 32np Mosaic-attribution tier matters most precisely when the
    # earlier attempts wedge, so the budget must reach it) and sizes each
    # child's cap from what remains — the session script's outer `timeout`
    # (6600s) must never fire mid-attempt: a SIGTERM to this driver would
    # orphan a --single child that still holds the tunnel and HBM, and the
    # next session step would block silently behind it.
    deadline = time.monotonic() + float(os.environ.get("MCPX_SMOKE_TOTAL_S", "6300"))
    # Ladder: full config, then halve the batch (HBM hypothesis), then the
    # same small batch without the Pallas kernel (Mosaic hypothesis). A
    # 32np success where 32 failed pins the failure on the kernel.
    batches = [
        b.strip()
        for b in os.environ.get("MCPX_SMOKE_BATCHES", "64,32,32np").split(",")
        if b.strip()
    ]
    floor = timeout_s + 60  # a COMPLETE attempt needs the full start watchdog
    for batch in batches:
        remaining = deadline - time.monotonic()
        if remaining < floor:
            # Not enough time for a complete bring-up: stop rather than
            # launch an attempt the budget would kill mid-start (a killed
            # attempt reads as "batch failed", falsely demoting the session
            # to model=test).
            print(
                f"smoke: {remaining:.0f}s left < {floor:.0f}s floor; skipping "
                f"batch={batch} and smaller",
                file=sys.stderr,
            )
            break
        # start watchdog + generate cap + compile/teardown slack, so the
        # child's own bounded failure paths normally fire first.
        plan_cap = float(os.environ.get("MCPX_SMOKE_PLAN_TIMEOUT_S", "720"))
        child_cap = min(timeout_s + plan_cap + 300, remaining)
        print(f"smoke: trying 2b batch={batch}", file=sys.stderr, flush=True)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--single", str(batch)],
                stdout=subprocess.PIPE,
                timeout=child_cap,
            )
        except subprocess.TimeoutExpired:
            print(f"smoke: batch={batch} hit driver cap {child_cap:.0f}s", file=sys.stderr)
            continue
        tail = [
            ln
            for ln in proc.stdout.decode(errors="replace").splitlines()
            if ln.startswith("{")
        ]
        if proc.returncode == 0 and tail:
            print(tail[-1], flush=True)
            return 0
    print(json.dumps({"ok": False, "batches_tried": batches}), flush=True)
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
