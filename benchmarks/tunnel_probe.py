"""Guarded TPU-tunnel liveness probe.

The axon relay's failure mode is a silent uninterruptible hang inside
``make_c_api_client`` (see BASELINE.md round-3 caveat), so the probe runs
``jax.devices()`` in a SUBPROCESS with a bounded poll and abandons it on
timeout — the parent never touches JAX. Exit 0 = tunnel alive, 1 = wedged.

Usage: ``python benchmarks/tunnel_probe.py [timeout_s]``
"""

from __future__ import annotations

import subprocess
import sys
import time


def probe(timeout_s: float = 60.0) -> bool:
    """True iff a fresh process can initialize the default JAX backend
    within ``timeout_s``. Shared by bench.py's ``_device_guard`` — keep the
    Popen/bounded-poll/abandon pattern in ONE place. No pipes (DEVNULL):
    a child stuck in a D-state kernel hang survives SIGKILL, and an
    unread pipe would add a second way to wedge; liveness is conveyed by
    the exit code alone."""
    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            break
        time.sleep(0.5)
    if proc.poll() is None:
        proc.kill()  # best-effort; NOT waited on (D-state survives SIGKILL)
        return False
    return proc.returncode == 0


if __name__ == "__main__":
    t = float(sys.argv[1]) if len(sys.argv) > 1 else 60.0
    ok = probe(t)
    print("tunnel:", "ALIVE" if ok else "WEDGED")
    sys.exit(0 if ok else 1)
