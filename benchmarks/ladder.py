#!/usr/bin/env python
"""Baseline config ladder — one run per BASELINE.json scenario.

The reference publishes no numbers (SURVEY.md §6); the operative baseline is
the driver-defined config ladder. Each scenario drives the REAL server stack
(aiohttp app, retrieval shortlist, grammar-constrained batched decode,
concurrent orchestrator over in-process fake microservices) and prints one
JSON line:

    {"config": N, "desc": ..., "value": ..., "unit": ..., ...}

Configs (BASELINE.json "configs"):
  1. single-intent /plan -> linear DAG          (3-service registry)
  2. /plan_and_execute, per-node retry+fallback (10-service registry)
  3. batched /plan bs=32, top-k retrieval       (100-service registry)
  4. telemetry-adaptive replanning loop
  5. 256-concurrent /plan_and_execute fan-out   (1k-service registry)

Model: "2b" on TPU, "test" on CPU (MCPX_BENCH_MODEL overrides).
"""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import sys
import time

# Runnable as `python benchmarks/ladder.py` from the repo root.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _pallas_on, _serving_announced

if int(os.environ.get("MCPX_LADDER_CPU", "0")) > 0:
    # Arm an N-device virtual CPU platform through the shared recipe — env
    # vars alone cannot evict the latched TPU backend, and the TPU tunnel
    # blocks (not errors) when another process holds it.
    from __graft_entry__ import _force_virtual_cpu

    _force_virtual_cpu(int(os.environ["MCPX_LADDER_CPU"]))


def _on_tpu() -> bool:
    import jax

    return jax.default_backend() not in ("cpu",)


def _config(model_size: str, max_batch: int = 32, checkpoint: str = "",
            shortlist_top_k: int = 8):
    from mcpx.core.config import MCPXConfig

    _serving_announced(max_batch, "ladder _config", tag="ladder")
    return MCPXConfig.from_dict(
        {
            # Same serving vocab as bench.py: in-tree BPE (models/bpe.py).
            "model": {"size": model_size, "max_seq_len": 2048, "vocab": "bpe",
                      "checkpoint_path": checkpoint},
            "engine": {
                "max_batch_size": max_batch,
                # SAME geometry as bench.py's BPE config (decode budget 64,
                # 4 x 64-token pages): every (batch, len) bucket executable
                # then comes out of the persistent XLA compilation cache the
                # headline bench already filled — a divergent geometry cost
                # config 3 of the r5 TPU ladder ~13 min of recompiles over
                # the tunnel before its outer timeout loomed.
                "max_decode_len": 64,
                "kv_page_size": 64,
                "max_pages_per_seq": 4,
                "temperature": 0.0,
                # bench._pallas_on: TPU backend, the session-wide
                # MCPX_BENCH_PALLAS gate (tpu_session.sh sets =0 when the
                # smoke only served with the Pallas kernel off), else the
                # smoke artifact's proven kernel config — one definition of
                # the knob, not a re-parse per script; announced via the
                # shared bench._serving_announced above.
                "use_pallas": _pallas_on(),
                "warmup_compile": _on_tpu(),
            },
            "planner": {"kind": "llm", "max_plan_retries": 0,
                        "shortlist_top_k": shortlist_top_k},
        }
    )


class _Stack:
    """Server + registry + fake local microservices for one scenario."""

    def __init__(self, n_services: int, model: str, *, fail: dict | None = None,
                 checkpoint: str = "", registry_seed: int = 7,
                 shortlist_top_k: int = 8):
        self.n_services = n_services
        self.model = model
        self.fail = fail or {}  # name -> "once" | "always"
        self.checkpoint = checkpoint
        self.registry_seed = registry_seed
        self.shortlist_top_k = shortlist_top_k

    async def __aenter__(self):
        from aiohttp.test_utils import TestServer

        from mcpx.orchestrator.transport import TransportError
        from mcpx.server.app import build_app
        from mcpx.server.factory import build_control_plane
        from mcpx.utils.synth import synth_registry

        self.cp = build_control_plane(
            _config(self.model, checkpoint=self.checkpoint,
                    shortlist_top_k=self.shortlist_top_k))
        self.records = synth_registry(self.n_services, seed=self.registry_seed)
        calls: dict[str, int] = {}

        def handler_for(name: str, mode: str | None):
            async def handler(payload):
                calls[name] = calls.get(name, 0) + 1
                if mode == "always" or (mode == "once" and calls[name] == 1):
                    raise TransportError(f"{name} injected failure")
                return {"service": name, "ok": True}

            return handler

        local = self.cp.orchestrator._transport.local
        for rec in self.records:
            await self.cp.registry.put(rec)
            local.register(rec.name, handler_for(rec.name, self.fail.get(rec.name)))
            for fb in rec.fallbacks:
                fb_name = fb.removeprefix("local://")
                # Fallbacks honour the fail map too — otherwise a "downed"
                # service recovers at the orchestrator level (its fallback
                # succeeds) and the retry/replan machinery is never reached.
                local.register(fb_name, handler_for(fb_name, self.fail.get(fb_name)))
        self.server = TestServer(build_app(self.cp))
        await self.server.start_server()
        self.base = f"http://{self.server.host}:{self.server.port}"

        import aiohttp

        self.session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=512)
        )
        try:
            # Wait for background engine bring-up (bounded — a wedged
            # startup must fail the scenario, not hang the ladder), then one
            # warmup round so no XLA compile lands in the timed region.
            deadline = time.monotonic() + 1200
            while True:
                async with self.session.get(f"{self.base}/healthz") as r:
                    h = await r.json()
                if h.get("engine") in ("ready", "n/a"):
                    break
                if h.get("engine") == "failed":
                    raise RuntimeError("engine failed during startup")
                if time.monotonic() > deadline:
                    raise RuntimeError("engine startup timed out")
                await asyncio.sleep(0.5)
            bs = self.cp.config.engine.max_batch_size
            await asyncio.gather(*(self.plan(f"warmup {i}") for i in range(bs)))
        except BaseException:
            await self.__aexit__()
            raise
        return self

    async def __aexit__(self, *exc):
        await self.session.close()
        await self.server.close()
        engine = getattr(self.cp.planner, "engine", None)
        if engine is not None and engine.state == "ready":
            await engine.aclose()

    async def plan(self, intent: str) -> dict:
        async with self.session.post(f"{self.base}/plan", json={"intent": intent}) as r:
            return {"status": r.status, **(await r.json())}

    def counter(self, name: str) -> float:
        c = getattr(self.cp.metrics, name)
        return c._value.get()

    async def plan_and_execute(self, intent: str, payload: dict) -> dict:
        async with self.session.post(
            f"{self.base}/plan_and_execute", json={"intent": intent, "payload": payload}
        ) as r:
            return {"http": r.status, **(await r.json())}


async def _seed_plan(cp, intent: str, names: list[str]) -> None:
    """Pre-seed the plan cache with a crafted linear plan over ``names`` so
    plan_and_execute(intent) deterministically executes those services —
    random-weight LLM decodes cannot be steered onto a specific service, and
    the retry/fallback/replan machinery only engages when the injected
    service is actually in the executed plan."""
    from mcpx.core.dag import Plan

    wire = {
        "nodes": [
            {"name": n, "service": n, "endpoint": f"local://{n}", "inputs": {}}
            for n in names
        ],
        "edges": [
            {"from": a, "to": b} for a, b in zip(names, names[1:])
        ],
    }
    plan = Plan.from_wire(wire)
    plan.intent = intent
    plan.origin = "seeded"
    cp._cache_put((intent, await cp.registry.version()), plan)


def _emit(config: int, desc: str, value, unit: str, **extra):
    print(
        json.dumps(
            {"config": config, "desc": desc, "value": round(value, 2), "unit": unit, **extra}
        ),
        flush=True,
    )


async def config1(model: str) -> None:
    """Single-intent /plan over a 3-service registry: p50 latency."""
    async with _Stack(3, model) as st:
        lat = []
        nodes = llm = 0
        for i in range(24):
            t0 = time.monotonic()
            res = await st.plan(f"fetch auth data then enrich the user record [{i}]")
            lat.append((time.monotonic() - t0) * 1e3)
            assert res["status"] == 200, res
            nodes = max(nodes, len(res["graph"]["nodes"]))
            llm += res.get("origin") == "llm"
        _emit(1, "single /plan p50 (3 services)", statistics.median(lat), "ms",
              max_plan_nodes=nodes, llm_share=llm / 24)


async def config2(model: str) -> None:
    """/plan_and_execute with retry + ordered fallback on a 10-service registry."""
    from mcpx.utils.synth import synth_registry

    records = synth_registry(10, seed=7)
    # One flaky service (first call fails -> retry) and one hard-down service
    # that has a declared fallback endpoint.
    flaky = records[0].name
    downed = next((r.name for r in records if r.fallbacks), records[1].name)
    async with _Stack(10, model, fail={flaky: "once", downed: "always"}) as st:
        # Mentioning the injected services steers retrieval's shortlist so
        # plans actually include them (random-weight decodes pick among the
        # shortlisted names).
        ok = retries = fallbacks = 0
        lat = []
        healthy = next(r.name for r in records
                       if r.name not in (flaky, downed) and not r.fallbacks)
        payload = {k: "x" for k in
                   ("query", "user_id", "order_id", "document", "text", "items", "amount",
                    "address", "score", "status", "report", "features", "vector", "summary")}
        for i in range(12):
            t0 = time.monotonic()
            intent = f"use {flaky} then {downed} then report [{i}]"
            await _seed_plan(st.cp, intent, [flaky, downed, healthy])
            res = await st.plan_and_execute(intent, payload)
            lat.append((time.monotonic() - t0) * 1e3)
            ok += res.get("status") in ("ok", "partial")
            for node in (res.get("trace") or {}).get("nodes", []):
                kinds = [a["kind"] for a in node.get("attempts", [])]
                retries += "retry" in kinds
                fallbacks += "fallback" in kinds
        _emit(2, "plan_and_execute p50 w/ retry+fallback (10 services)",
              statistics.median(lat), "ms", ok=ok, total=12, ok_rate=ok / 12,
              plan_source="seeded-cache (deterministic injection coverage)",
              retries_exercised=retries, fallbacks_exercised=fallbacks)


async def config3(model: str) -> None:
    """Batched /plan bs=32 with top-k retrieval over 100 services."""
    import random

    from mcpx.utils.synth import intent_for

    async with _Stack(100, model) as st:
        rng = random.Random(3)
        intents = [f"{intent_for(st.records, rng)} [{i}]" for i in range(96)]
        fwd0, tok0 = st.counter("decode_forwards"), st.counter("decode_tokens")
        t0 = time.monotonic()
        results = await asyncio.gather(*(st.plan(i) for i in intents))
        dt = time.monotonic() - t0
        assert all(r["status"] == 200 for r in results)
        llm = sum(r.get("origin") == "llm" for r in results)
        fwd = st.counter("decode_forwards") - fwd0
        tok = st.counter("decode_tokens") - tok0
        # Batching proof: with a shared slab + speculation, model forwards
        # must be far fewer than requests (96 serial unbatched plans would
        # need >= 96 * min-plan-length forwards). A regression to serial
        # decoding fails here rather than shipping a slow-but-green number.
        assert fwd < len(intents) * 4, (
            f"batching regressed: {fwd} forwards for {len(intents)} plans")
        # Quality of the served plans vs their intents (suffix stripped:
        # the cache-busting " [i]" tag is not intent content).
        from mcpx.planner.quality import mean_quality, plan_quality

        by_name = {r.name: r for r in st.records}
        q = mean_quality(
            plan_quality(r.get("graph") or {}, intent.rsplit(" [", 1)[0], by_name)
            for intent, r in zip(intents, results)
        )
        _emit(3, "batched /plan throughput, top-k retrieval (100 services)",
              len(intents) / dt, "plans/s", concurrency=96,
              engine_batch=st.cp.config.engine.max_batch_size,
              llm_share=llm / len(intents), decode_forwards=int(fwd),
              tok_per_forward=round(tok / max(1.0, fwd), 2),
              quality=round(q["score"], 3),
              quality_coverage=round(q["coverage"], 3))


async def config4(model: str) -> None:
    """Telemetry-adaptive replanning: a degraded service gets planned around."""
    from mcpx.utils.synth import synth_registry

    records = synth_registry(10, seed=7)
    # A service that is hard-down INCLUDING its declared fallback: only the
    # telemetry-driven replan can route around it (baseline config 4).
    bad_rec = next((r for r in records if r.fallbacks), records[2])
    bad = bad_rec.name
    fails = {bad: "always"}
    for fb in bad_rec.fallbacks:
        fails[fb.removeprefix("local://")] = "always"
    async with _Stack(10, model, fail=fails) as st:
        payload = {"query": "q", "user_id": "u", "items": "i", "document": "d",
                   "amount": "1", "report": "r", "score": "s", "text": "t"}
        recovered = replans = 0
        n = 10
        healthy = next(r.name for r in records if r.name not in fails)
        for i in range(n):
            intent = f"use {bad} to enrich order data then report it [{i}]"
            # Seeded plan includes the hard-down service (fallback also down):
            # only a telemetry-driven replan around it can succeed.
            await _seed_plan(st.cp, intent, [bad, healthy])
            res = await st.plan_and_execute(intent, payload)
            replans += res.get("replans", 0)
            recovered += res.get("status") == "ok" and res.get("replans", 0) > 0
        _emit(4, "telemetry-adaptive replanning (degraded service)",
              replans, "replans", recovered_requests=recovered, requests=n)


async def config5(model: str) -> None:
    """256 concurrent /plan_and_execute fan-out/fan-in over 1k services."""
    import random

    from mcpx.utils.synth import intent_for

    async with _Stack(1000, model) as st:
        rng = random.Random(5)
        payload = {k: "x" for k in
                   ("query", "user_id", "order_id", "document", "text", "items", "amount",
                    "address", "score", "status", "report", "features", "vector", "summary")}
        intents = [f"{intent_for(st.records, rng, 4)} fan out and merge [{i}]"
                   for i in range(256)]
        t0 = time.monotonic()
        results = await asyncio.gather(
            *(st.plan_and_execute(i, payload) for i in intents)
        )
        dt = time.monotonic() - t0
        ok = sum(r.get("status") in ("ok", "partial") for r in results)
        llm = sum(r.get("origin") == "llm" for r in results)
        http_ok = sum(r.get("http") == 200 for r in results)
        # llm_share over ANSWERED requests: a closed-loop tail that trips the
        # server's request timeout (CPU-speed artifact) has no origin at all
        # and must not masquerade as a heuristic fallback.
        _emit(5, "256-concurrent plan_and_execute (1k services)",
              len(intents) / dt, "req/s", ok=ok, total=len(intents),
              http_ok=http_ok, ok_rate=ok / max(1, http_ok),
              llm_share=llm / max(1, http_ok))


async def config6(model: str) -> None:
    """Beyond the BASELINE set: plan quality of the committed TRAINED
    planner checkpoint through the served stack (random weights score the
    registry base rate here — VERDICT r3 next #3). Skips with a stub line
    when no artifact is committed. Always serves the tiny trained model
    (the checkpoint is size 'test'), whatever the ladder's headline model."""
    import random

    from mcpx.planner.quality import mean_quality, plan_quality
    from mcpx.utils.synth import intent_for

    # One source of truth for the artifact path + override (bench.py's).
    from bench import _TRAINED_CKPT

    ckpt = os.environ.get("MCPX_BENCH_QUALITY_CHECKPOINT", _TRAINED_CKPT)
    if not os.path.exists(ckpt):
        _emit(6, "trained-checkpoint plan quality (extra)", 0, "score",
              skipped="no committed checkpoint")
        return
    # registry_seed=0 and shortlist_top_k=6: the registry and prompt
    # geometry this checkpoint was trained to serve (models/corpus.py — a
    # deployment artifact, like the grammar); intents are fresh draws.
    async with _Stack(
        1000, "test", checkpoint=ckpt, registry_seed=0, shortlist_top_k=6
    ) as st:
        rng = random.Random(99)
        by_name = {r.name: r for r in st.records}
        rows, llm = [], 0
        for i in range(32):
            intent = intent_for(st.records, rng, rng.randint(2, 4))
            r = await st.plan(f"{intent} [{i}]")
            assert r["status"] == 200
            llm += r.get("origin") == "llm"
            rows.append(plan_quality(r.get("graph") or {}, intent, by_name))
        # Honesty gate: the heuristic fallback IS the training teacher, so
        # a broken checkpoint load would otherwise emit the teacher's high
        # score while never exercising the model.
        assert llm / 32 >= 0.95, (
            f"trained-quality degenerate: llm_share={llm / 32:.2f} — plans came "
            "from the heuristic fallback (the teacher), not the checkpoint")
        q = mean_quality(rows)
        _emit(6, "trained-checkpoint plan quality (extra)", q["score"], "score",
              coverage=round(q["coverage"], 3), relevance=round(q["relevance"], 3),
              coherence=round(q["coherence"], 3), n=q["n"], llm_share=llm / 32)


CONFIGS = [config1, config2, config3, config4, config5, config6]


async def main() -> None:
    model = os.environ.get("MCPX_BENCH_MODEL") or ("2b" if _on_tpu() else "test")
    only = os.environ.get("MCPX_LADDER_ONLY")
    for i, cfg in enumerate(CONFIGS, start=1):
        if only and str(i) not in only.split(","):
            continue
        await cfg(model)


def _main_isolated() -> None:
    """Run each config in its own subprocess: every scenario boots a fresh
    multi-GB engine, and per-process isolation is what guarantees HBM comes
    back between scenarios."""
    import subprocess

    only = os.environ.get("MCPX_LADDER_ONLY")
    ids = only.split(",") if only else [str(i) for i in range(1, len(CONFIGS) + 1)]
    failures = 0
    for i in ids:
        env = dict(os.environ, MCPX_LADDER_ONLY=i, MCPX_LADDER_CHILD="1")
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)], env=env)
        failures += proc.returncode != 0
    if failures:
        raise SystemExit(f"{failures}/{len(ids)} ladder configs failed")


if __name__ == "__main__":
    if os.environ.get("MCPX_LADDER_CHILD"):
        asyncio.run(main())
    else:
        _main_isolated()
