#!/usr/bin/env python
"""North-star benchmark: plans/sec through the real serving stack.

Measures `POST /plan` end-to-end — aiohttp server, retrieval shortlist over a
1,000-service registry, prompt build, grammar-constrained batched decode on
the inference engine, validation/repair — and prints ONE JSON line:

    {"metric": "plans_per_sec", "value": N, "unit": "plans/s", "vs_baseline": N/100}

vs_baseline is against the north-star target of 100 plans/sec (BASELINE.md;
the reference publishes no numbers of its own, SURVEY.md §6).

Environment knobs:
    MCPX_BENCH_MODEL     model size ("2b" default on TPU, "test" on CPU)
    MCPX_BENCH_REQUESTS  total /plan requests (default 512)
    MCPX_BENCH_CONCURRENCY  in-flight requests (default 256)
    MCPX_BENCH_SERVICES  registry size (default 1000)
"""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import sys
import time


def _build_config(model_size: str):
    from mcpx.core.config import MCPXConfig

    return MCPXConfig.from_dict(
        {
            "model": {"size": model_size, "max_seq_len": 2048},
            "engine": {
                "max_batch_size": 64,
                "max_decode_len": 96,
                # 64-token pages: measured 1.6x faster decode than 16-token
                # pages (4x fewer page DMAs per attention program) with no
                # fragmentation cost at this workload's uniform lengths.
                "kv_page_size": 64,
                # Sized to the workload: 768-token prompt bucket + 96 decode
                # + speculation slack; oversizing the page table inflates
                # every attention gather.
                "max_pages_per_seq": 16,
                "temperature": 0.0,
                "use_pallas": True,
                # Pallas kernels need a real TPU; interpret mode on CPU.
                "interpret": False,
                # Compile every (B, T) bucket before serving: the timed
                # region must contain zero XLA compiles.
                "warmup_compile": True,
            },
            "planner": {
                "kind": "llm",
                # One constrained decode per plan; validation failures repair
                # via the heuristic (worst-case cost path for random weights).
                "max_plan_retries": 0,
                # 6-way shortlist keeps the compact prompt inside the
                # 768-token prefill bucket (8-way spills into 1024).
                "shortlist_top_k": 6,
            },
        }
    )


async def _run(model_size: str, n_requests: int, concurrency: int, n_services: int) -> dict:
    from aiohttp import ClientSession, TCPConnector
    from aiohttp.test_utils import TestServer

    from mcpx.server.app import build_app
    from mcpx.server.factory import build_control_plane
    from mcpx.utils.synth import synth_registry

    import random

    cfg = _build_config(model_size)
    if not _on_tpu():
        cfg.engine.use_pallas = False
    cp = build_control_plane(cfg)
    for rec in synth_registry(n_services, seed=7):
        await cp.registry.put(rec)

    app = build_app(cp)
    server = TestServer(app)
    await server.start_server()
    base = f"http://{server.host}:{server.port}"

    rng = random.Random(11)
    from mcpx.utils.synth import intent_for

    records = await cp.registry.list_services()
    intents = [f"{intent_for(records, rng)} [{i}]" for i in range(n_requests)]

    t_setup0 = time.monotonic()
    async with ClientSession(connector=TCPConnector(limit=concurrency)) as session:
        # Engine bring-up runs as a server background task; wait for
        # /healthz to report ready before the request warmup (this also
        # exercises the warming-state health surface).
        while True:
            async with session.get(f"{base}/healthz") as resp:
                health = await resp.json()
            if health.get("engine") in ("ready", "n/a", None):
                break
            if health.get("engine") == "failed":
                raise RuntimeError("engine failed during startup")
            await asyncio.sleep(1.0)
        # Warmup: trigger engine startup + compile for the hot batch buckets.
        async def warm_one(w: str) -> int:
            async with session.post(f"{base}/plan", json={"intent": w}) as resp:
                await resp.read()
                return resp.status

        warm = [f"warmup intent {i}" for i in range(cfg.engine.max_batch_size)]
        statuses = await asyncio.gather(*(warm_one(w) for w in warm))
        bad = [s for s in statuses if s != 200]
        if bad:
            raise RuntimeError(f"warmup failed: {len(bad)}/{len(warm)} non-200 responses")
        warmup_s = time.monotonic() - t_setup0

        latencies: list[float] = []
        sem = asyncio.Semaphore(concurrency)
        errors = 0

        async def one(intent: str) -> None:
            nonlocal errors
            async with sem:
                t0 = time.monotonic()
                async with session.post(f"{base}/plan", json={"intent": intent}) as resp:
                    await resp.read()
                    if resp.status != 200:
                        errors += 1
                latencies.append((time.monotonic() - t0) * 1e3)

        t0 = time.monotonic()
        await asyncio.gather(*(one(i) for i in intents))
        elapsed = time.monotonic() - t0

    await server.close()
    engine = getattr(cp.planner, "engine", None)
    if engine is not None and engine.state == "ready":
        await engine.aclose()

    if errors > max(1, n_requests // 100):
        raise RuntimeError(f"{errors}/{n_requests} requests failed")
    lat = sorted(latencies)
    return {
        "plans_per_sec": n_requests / elapsed,
        "p50_ms": statistics.median(lat),
        "p99_ms": lat[int(0.99 * (len(lat) - 1))],
        "elapsed_s": elapsed,
        "warmup_s": warmup_s,
        "errors": errors,
    }


def _on_tpu() -> bool:
    import jax

    return jax.default_backend() not in ("cpu",)


def main() -> None:
    model = os.environ.get("MCPX_BENCH_MODEL")
    n_requests = int(os.environ.get("MCPX_BENCH_REQUESTS", "512"))
    concurrency = int(os.environ.get("MCPX_BENCH_CONCURRENCY", "256"))
    n_services = int(os.environ.get("MCPX_BENCH_SERVICES", "1000"))
    if model is None:
        model = "2b" if _on_tpu() else "test"

    try:
        stats = asyncio.run(_run(model, n_requests, concurrency, n_services))
    except Exception as e:  # noqa: BLE001 - one fallback tier, then report
        print(f"bench: model={model} failed ({type(e).__name__}: {e}); retrying size=test",
              file=sys.stderr)
        model = "test"
        stats = asyncio.run(_run(model, n_requests, concurrency, n_services))

    value = round(stats["plans_per_sec"], 2)
    print(
        json.dumps(
            {
                "metric": "plans_per_sec",
                "value": value,
                "unit": "plans/s",
                "vs_baseline": round(value / 100.0, 3),
                "p50_ms": round(stats["p50_ms"], 1),
                "p99_ms": round(stats["p99_ms"], 1),
                "model": model,
                "n_services": n_services,
                "requests": n_requests,
                "errors": stats["errors"],
            }
        )
    )


if __name__ == "__main__":
    main()
