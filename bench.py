#!/usr/bin/env python
"""North-star benchmark: plans/sec + honest latency through the real stack.

Two phases against the live aiohttp server (retrieval shortlist over a
1,000-service registry, prompt build, grammar-constrained continuously-batched
decode on the inference engine, validation/repair):

  1. **Saturation (closed loop)**: MCPX_BENCH_CONCURRENCY in-flight requests
     until MCPX_BENCH_REQUESTS complete → plans/sec. (Closed-loop latency at
     256-way concurrency is just Little's law — queue depth / throughput — so
     it is reported as ``sat_p50_ms`` but is NOT the latency claim.)
  2. **Latency (open loop)**: requests fired on a fixed arrival schedule at
     MCPX_BENCH_RATE_FRACTION (default 0.7) of the measured throughput,
     regardless of completions → p50/p99 the way the north star means them
     ("p50 <150 ms at 100 plans/s" is an offered-load statement).

Honesty gates (VERDICT r2 #3/#7): the run FAILS loudly unless ≥95% of plans
are LLM-authored (``origin`` field per response — a bench where every plan
fell back to the heuristic must not print a clean line), and the output
carries llm_share, decode tok/s, model forwards, speculation amortisation,
goodput MFU and the queue/prefill/decode phase split scraped from /metrics.

Prints ONE JSON line:

    {"metric": "plans_per_sec", "value": N, "unit": "plans/s",
     "vs_baseline": N/100, "p50_ms": ..., "llm_share": ..., "mfu": ..., ...}

vs_baseline is against the north-star target of 100 plans/sec (BASELINE.md;
the reference publishes no numbers of its own, SURVEY.md §6).

The output also carries the roofline cost observatory (ISSUE 7,
docs/observability.md): a per-phase ``roofline`` block from GET /costs
deltas (XLA cost_analysis — achieved FLOP/s, bytes/s, arithmetic
intensity, mfu vs device peaks; ``mfu_basis="xla_cost_analysis"`` where
the backend publishes costs, labeled fallback otherwise), ``pallas_reason``
(why the Pallas kernel path is off, next to the ``pallas`` flag), and a
``regression`` verdict of this run against the committed BENCH_r*.json
series (mcpx/cli/bench_report.py — the same report `mcpx bench report`
computes offline).

Environment knobs:
    MCPX_BENCH_MODEL     model size ("2b" default on TPU, "test" on CPU)
    MCPX_BENCH_BATCH     engine max_batch_size (default 64; lower on HBM OOM)
    MCPX_BENCH_REQUESTS  total /plan requests in phase 1 (default 512)
    MCPX_BENCH_CONCURRENCY  in-flight requests in phase 1 (default 256)
    MCPX_BENCH_SERVICES  registry size (default 1000)
    MCPX_BENCH_RATE_FRACTION  phase-2 offered load as a fraction of measured
                              throughput (default 0.7)
    MCPX_BENCH_LATENCY_REQUESTS  phase-2 request count (default 192)
    MCPX_BENCH_PALLAS    0 = fused-jnp attention (smoke ladder / jnp proxy);
                         default: ragged kernel on — Mosaic on TPU, the
                         Pallas interpreter on the CPU proxy (ISSUE 15)
    MCPX_BENCH_OVERLOAD  0 skips the scheduler overload phase (default on)
    MCPX_BENCH_MIXED     0 skips the heterogeneous mixed-traffic phase
                         (default on): constrained/free-form + two
                         temperatures + two grammars, served closed-loop
                         with engine.hetero_batch on vs off at the same
                         offered load — reports mixed_plans_per_sec per
                         mode, the speedup, HoL-wait p99 and degraded_share
    MCPX_BENCH_MIXED_REQUESTS     mixed-phase request count (default 96)
    MCPX_BENCH_MIXED_TEMPERATURE  the phase's hot sampling temperature (0.7)
    MCPX_BENCH_HETERO    1 = serve the HEADLINE phases with
                         engine.hetero_batch on too (default 0 keeps the
                         headline comparable to earlier rounds)
    MCPX_BENCH_TRACE     0 skips the latency-attribution phase (default on):
                         a short open-loop round at the phase-2 rate with
                         the request tracer attached — reports p50/p99
                         scheduler-queue/admit-wait/prefill/decode/tool
                         shares in the output JSON (headline phases always
                         run tracing-disabled)
    MCPX_BENCH_TRACE_REQUESTS     attribution-phase request count (default 96)
    MCPX_BENCH_CHAOS     0 skips the chaos resilience phase (default on):
                         the orchestrator's transport wrapped in a seeded
                         fault injector (flapping/erroring primaries,
                         healthy fallbacks), the same /execute workload
                         served with resilience OFF then ON — reports
                         chaos_success_rate / chaos_success_rate_baseline /
                         deadline_overrun_share (success = ok within the
                         per-request deadline header)
    MCPX_BENCH_CHAOS_REQUESTS     chaos-phase request count per mode (160)
    MCPX_BENCH_CHAOS_DEADLINE_MS  chaos-phase per-request deadline (400)
    MCPX_BENCH_SPEC      0 skips the speculative-decoding phase (default
                         on): the same mixed engine stream served twice at
                         the same offered load — speculation OFF (a true
                         per-token baseline: no drafter, DFA fast-forward
                         disabled, one forward per token) then ON (the
                         grammar-aware recurrent drafter + one batched
                         [rows, K+1] verify) — on a DEDICATED single-device
                         engine (1×1 mesh, serving geometry otherwise):
                         speculation is a per-chip decode economics lever,
                         and the CPU fallback's 8-way virtual mesh would
                         bill its serialized-collective simulation overhead
                         to the OFF→ON delta. Reports spec_decode_tok_s /
                         spec_speedup (tokens-per-forward ON/OFF — the
                         bandwidth-bound-decode speedup; wall-clock ratio
                         reported as spec_wall_speedup) / spec_accept_rate
                         (overall + per constrained/free row class) and
                         checks greedy outputs byte-identical across modes
    MCPX_BENCH_SPEC_REQUESTS      spec-phase request count per mode (192,
                         served as 3 interleaved OFF/ON rounds; each mode
                         reports its best round so co-tenant CPU bursts
                         must poison a whole mode, not one window, to
                         skew the speedup)
    MCPX_BENCH_SPEC_K    draft window width k for the spec phase and (with
                         MCPX_BENCH_SPEC_HEADLINE) the headline engine
                         (default: EngineConfig.speculative.k)
    MCPX_BENCH_SPEC_HEADLINE      1 = serve the HEADLINE phases with
                         speculation on too (forces hetero_batch; default 0
                         keeps the headline comparable to earlier rounds)
    MCPX_BENCH_PREFIX    0 skips the radix prefix KV reuse phase (default
                         on): the same repeat-heavy intent stream planned
                         with engine.prefix_cache off vs on →
                         prefill_tokens_per_request per mode, prefix hit
                         rates, and COLD vs WARM replan p50 (a warm replan
                         continues decoding from the cached prefix with the
                         exclusions spliced into the prompt suffix) in the
                         output JSON
    MCPX_BENCH_PREFIX_INTENTS     unique intents in the phase pool (8)
    MCPX_BENCH_PREFIX_REPS        repeats per unique intent (8)
    MCPX_BENCH_PREFIX_REPLANS     replans timed per mode (6)
    MCPX_BENCH_TIER      0 skips the tiered KV cache phase (default on):
                         dedicated small engines drive a working set
                         >= 10x the HBM-resident radix cap with the
                         host-RAM spill tier off vs on -> token-hit-rate
                         retention, per-tenant isolation under an
                         adversarial thrash tenant, warm-restart
                         first-plan prefill, and seeded spill chaos
                         (copy-latency spikes + host-alloc failures)
    MCPX_BENCH_TIER_PROMPTS       unique prompts in the tier working set (64)
    MCPX_BENCH_TIER_ROUNDS        round-robin passes over the set (3)
    MCPX_BENCH_PREFIX_SAT         0 skips the warm-replan-at-saturation
                         sub-scenario of phase 8 (default on): warm
                         replans timed while background traffic keeps the
                         slab full -> replan_warm_sat_p50_ms top-level.
    MCPX_BENCH_FLIGHT    0 skips the flight-recorder phase (default on):
                         the same direct-plan stream served with the
                         recorder + decode-loop worker profiler off vs on
                         (live attach) -> flight_overhead_frac (<3%
                         acceptance) + the worker_profile block (named
                         worker-loop phases, >=95% attribution).
    MCPX_BENCH_FLIGHT_REQUESTS    flight-phase request count per round (96)
    MCPX_BENCH_LEDGER    0 skips the cost-ledger phase (default on): the
                         same direct-plan stream served with the
                         per-request ledger + SLO observe off vs on
                         (live attach) -> ledger_overhead_frac (<3%
                         acceptance) + the attribution block (per-tenant
                         itemized usage, wall-attribution fraction,
                         FLOP conservation verdict).
    MCPX_BENCH_LEDGER_REQUESTS    ledger-phase request count per round (96)
    MCPX_BENCH_KERNEL    0 skips the ragged-kernel/fused-dispatch phase
                         (default on): per-step vs fused decode dispatch
                         at the same offered load on a dedicated 1×1
                         engine → decode_dispatches_per_token +
                         fused_decode_speedup top-level, plus the
                         kernel-vs-jnp interpret-parity gate
                         (BenchGateError on greedy divergence)
    MCPX_BENCH_KERNEL_REQUESTS    kernel-phase request count (48)
    MCPX_BENCH_OVERLOAD_FACTOR    offered load as a multiple of measured
                                  throughput (default 4)
    MCPX_BENCH_OVERLOAD_REQUESTS  overload-phase request count (default 256)
    MCPX_BENCH_SLO_MS    overload-phase SLO / per-request deadline (default 1000)
    MCPX_BENCH_TICK / _DEPTH / _MINFREE / _WAIT / _SPECULATE_K / _DRAFT
                         worker-loop levers (decode_steps_per_tick,
                         pipeline_depth, admit_min_free, admit_max_wait_s,
                         speculate_k, draft_mode) — bake the probe sweep's
                         p50-optimal point into the headline run. (The
                         fast-forward-width lever was MCPX_BENCH_SPEC
                         before the speculative-decoding phase claimed
                         that name.)
"""

from __future__ import annotations

import asyncio
import json
import os
import re
import statistics
import sys
import time

# bf16 peak per chip, by jax device_kind substring; MFU is only reported when
# the hardware is recognised (a hard-coded peak on unknown chips would print
# a confidently-wrong headline number).
_PEAK_FLOPS_BY_KIND = (
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v6e", 918e12),
    ("v6 lite", 918e12),
)


def _peak_flops_per_chip() -> float | None:
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for sub, peak in _PEAK_FLOPS_BY_KIND:
        if sub in kind:
            return peak
    return None


def _measured_peak_flops() -> float:
    """Achievable dense-matmul FLOPs/s of the default backend, MEASURED
    (best of a few timed f32 matmuls after a compile warm-up) — the MFU
    denominator on hardware with no datasheet entry (the CPU proxy). A
    measured peak can never print a confidently-wrong datasheet fraction:
    the reported number is 'share of what a dense matmul actually achieves
    here', labeled via mfu_basis."""
    import jax
    import jax.numpy as jnp

    n = 1024
    a = jnp.ones((n, n), jnp.float32)
    b = jnp.ones((n, n), jnp.float32)
    f = jax.jit(lambda x, y: x @ y)
    f(a, b).block_until_ready()  # compile outside the timed reps
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        f(a, b).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return 2.0 * n**3 / max(1e-9, best)


class BenchGateError(RuntimeError):
    """Honesty-gate failure (llm_share, error rate): must FAIL the bench,
    never be swallowed by the model-size fallback retry."""


def _roofline_block(
    costs0,
    costs1,
    costs2,
    sat_wall: float,
    open_wall: float,
    peak_flops: "float | None",
    peak_flops_basis: "str | None",
    peak_bytes: "float | None",
    mfu_analytic: "float | None",
    analytic_flops: float,
) -> dict:
    """Per-phase roofline from GET /costs snapshots (XLA cost_analysis
    totals, mcpx/telemetry/costs.py): achieved FLOP/s, achieved bytes/s,
    arithmetic intensity and position against the device peaks, for the
    saturation and open-loop phases. ``basis`` labels whether the numbers
    are XLA-derived or the accounting was unavailable (scrape failed, cost
    analysis unsupported) — never silently absent. The analytic
    2·params·tokens model rides along as a cross-check: ``xla_vs_analytic``
    is XLA-counted phase flops over the analytic bill, so a drifting ratio
    says the analytic model is mis-billing (attention, drafter, padding)."""
    # stdlib-safe: rounded_roofline touches no jax. One precision contract
    # with the engine's span attrs (costs._ROOFLINE_ROUNDING).
    from mcpx.telemetry.costs import rounded_roofline

    def totals(c):
        if not isinstance(c, dict):
            return None
        return (c.get("engine") or {}).get("totals")

    def phase(c_lo, c_hi, wall):
        t_lo, t_hi = totals(c_lo), totals(c_hi)
        if t_lo is None or t_hi is None or wall <= 0:
            return None
        df = (t_hi.get("flops_executed") or 0.0) - (t_lo.get("flops_executed") or 0.0)
        db = (t_hi.get("bytes_executed") or 0.0) - (t_lo.get("bytes_executed") or 0.0)
        if df <= 0:
            return None
        rl = rounded_roofline(
            df, db or None, wall, peak_flops=peak_flops, peak_bytes_s=peak_bytes
        )
        return {
            "flops": df,
            "bytes_accessed": db,
            "wall_s": round(wall, 3),
            "achieved_flops_s": rl.get("achieved_flops_s"),
            "achieved_bytes_s": rl.get("achieved_bytes_s"),
            "arithmetic_intensity": rl.get("arithmetic_intensity"),
            "mfu": rl.get("mfu"),
            "hbm_bw_util": rl.get("hbm_bw_util"),
            "bound": rl.get("bound"),
        }

    sat = phase(costs0, costs1, sat_wall)
    open_ = phase(costs1, costs2, open_wall)
    basis = "xla_cost_analysis" if sat is not None else "unavailable"
    return {
        "basis": basis,
        "mfu_basis": basis,
        "peak_flops": peak_flops,
        "peak_flops_basis": peak_flops_basis,
        "peak_bytes_s": peak_bytes,
        "phases": {"sat": sat, "open": open_},
        "mfu_analytic": round(mfu_analytic, 6) if mfu_analytic is not None else None,
        "xla_vs_analytic": (
            round(sat["flops"] / analytic_flops, 4)
            if sat is not None and analytic_flops > 0
            else None
        ),
    }


def _sp_bench_model(n_pieces: int) -> str:
    """Generate (once, cached) a large synthetic SentencePiece model for the
    real-checkpoint serving configuration bench (VERDICT r4 next #5): the
    committed BPE numbers dodge the 256k-vocab unembed cost, the SP-trie
    sparse grammar build, and SP decode-length distributions — this fixture
    measures them without real Gemma weights. Pieces: the planner/registry
    fragment set (realistic active columns for the grammar) + unique filler
    to reach real-Gemma vocab scale (unembed cost depends only on V)."""
    if n_pieces < 1024:
        raise ValueError(f"MCPX_BENCH_SP_PIECES={n_pieces}: need >= 1024")
    from mcpx.models.sp_model import tiny_model
    from mcpx.utils.synth import _DOMAINS, _KEYS, _VERBS

    # Cache key carries a recipe hash so editing the piece construction (or
    # the synth word lists) regenerates instead of serving a stale vocab.
    import hashlib
    import inspect

    recipe = inspect.getsource(_sp_bench_model) + repr((_DOMAINS, _VERBS, _KEYS))
    tag = hashlib.sha1(recipe.encode()).hexdigest()[:8]
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarks",
        f".sp_bench_{n_pieces}_{tag}.model",
    )
    if os.path.exists(path):
        return path

    words: list[tuple[str, float]] = []
    seen: set[str] = set()

    def add(piece: str, score: float) -> None:
        if piece and piece not in seen:
            seen.add(piece)
            words.append((piece, score))

    for frag in (
        '{"steps":[{"s":"', '","in":["', '"],"next":["', '"],"next":[]}',
        '"]}]}', '","', '"],"', "-", '"', ":", "{", "}", "[", "]",
    ):
        add(frag, -1.5)
    for w in _DOMAINS + _VERBS + _KEYS + ["then", "please", "and", "for"]:
        add(w, -2.0)
        add("▁" + w, -2.2)
    for d in _DOMAINS:
        for v in _VERBS:
            add(f"{d}-{v}-", -2.5)
    for i in range(min(10000, max(0, n_pieces // 4))):
        add(f"{i:04d}", -3.0)
    words = words[: max(0, n_pieces - 260)]
    i = 0
    while len(words) < n_pieces - 260:
        add(f"flr{i:06x}", -9.0)  # filler: inert, pads V to Gemma scale
        i += 1
    m = tiny_model(extra_pieces=words)
    tmp = path + f".tmp{os.getpid()}"  # pid: concurrent benches never share
    m.save(tmp)
    os.replace(tmp, path)
    return path


def _build_config(model_size: str):
    from mcpx.core.config import MCPXConfig

    vocab_mode = os.environ.get("MCPX_BENCH_VOCAB", "bpe")
    if vocab_mode not in ("bpe", "sp"):
        raise ValueError(f"MCPX_BENCH_VOCAB={vocab_mode!r}: expected bpe|sp")
    if vocab_mode == "sp":
        # Real-checkpoint serving configuration: SentencePiece vocab at
        # real-Gemma scale (256k default), sparse-trie grammar, bigger page
        # budget (SP planner text tokenizes longer than the workload-fitted
        # BPE vocab; MCPX_BENCH_SP_PIECES overrides the vocab size).
        n_pieces = int(os.environ.get("MCPX_BENCH_SP_PIECES", "256000"))
        vocab = "sp:" + _sp_bench_model(n_pieces)
        pages_cfg = {"max_decode_len": 48, "kv_page_size": 64, "max_pages_per_seq": 8}
    else:
        vocab = "bpe"
        # 64-token decode budget = the training-corpus target geometry
        # (models/corpus.py seq_len 192 - 128 prompt). The previous 40 was
        # picked for throughput but CLIPPED ~70% of teacher-grade plans
        # (measured: mean 42.6 tokens, p99 53) — the grammar's
        # distance-to-accept steering closes plans early near the budget,
        # so the bench was timing structurally under-sized plans. 128+64+
        # speculation slack still fits 4 x 64-token pages.
        pages_cfg = {"max_decode_len": 64, "kv_page_size": 64, "max_pages_per_seq": 4}

    return MCPXConfig.from_dict(
        {
            # In-tree BPE vocab (models/bpe.py): ~6x fewer prompt tokens and
            # ~8x fewer plan tokens than the byte vocab — prefill drops from
            # the 512-token bucket to 128, decode from ~90 to ~20 tokens.
            # CAVEAT: the committed vocab is trained on this bench's own
            # synthetic registry distribution (bpe.py docstring); real
            # registries with different naming compress materially worse —
            # real-checkpoint serving uses the SentencePiece vocab instead.
            "model": {
                "size": model_size,
                "max_seq_len": 2048,
                "vocab": vocab,
                # MCPX_BENCH_QUANTIZE=int8: weight-only int8 serving
                # (models/gemma/quant.py) — halves HBM bytes-at-rest and
                # the decode weight-streaming bill.
                "quantize": os.environ.get("MCPX_BENCH_QUANTIZE", "none"),
            },
            "engine": {
                # MCPX_BENCH_BATCH: HBM-pressure escape hatch — engine slab
                # rows scale KV pools + per-bucket executables linearly, so
                # halving this is the first move when 2b startup hits
                # RESOURCE_EXHAUSTED on a single chip. Unset, the default is
                # the batch the startup smoke PROVED on this hardware
                # (benchmarks/smoke_tpu.json) — the driver's round-end run
                # has no session script to export the proven value, and the
                # one measured batch-64 attempt wedged the first generate.
                "max_batch_size": _bench_batch(model_size),
                # Decode budget is an INFORMATION budget: 40 BPE tokens carry
                # more JSON than the 96 byte-tokens the old config allowed
                # (measured ~6-8 chars/token on plan text). Oversizing it
                # lets the grammar emit sprawling plans and multiplies decode
                # forwards per request (probe: budget 96 cost 2.5x the
                # forwards of 32 for the same request count).
                # 64-token pages: measured 1.6x faster decode than 16-token
                # pages (4x fewer page DMAs per attention program) with no
                # fragmentation cost at this workload's uniform lengths.
                # BPE prompts fit the 128-token prefill bucket + the decode
                # budget + speculation slack in 4 x 64-token pages (SP mode
                # doubles the page budget — see pages_cfg above).
                **pages_cfg,
                # Worker-loop levers, overridable so the probe sweep's
                # p50-optimal point can be served by the headline bench
                # without a code change (VERDICT r4 next #2). Defaults =
                # EngineConfig defaults.
                **{
                    cfg_key: conv(os.environ[env])
                    for env, cfg_key, conv in (
                        ("MCPX_BENCH_TICK", "decode_steps_per_tick", int),
                        ("MCPX_BENCH_DEPTH", "pipeline_depth", int),
                        ("MCPX_BENCH_MINFREE", "admit_min_free", int),
                        ("MCPX_BENCH_WAIT", "admit_max_wait_s", float),
                        ("MCPX_BENCH_SPECULATE_K", "speculate_k", int),
                        ("MCPX_BENCH_DRAFT", "draft_mode", str),
                    )
                    if env in os.environ
                },
                "temperature": 0.0,
                # Kernel route (ISSUE 15): ON by default on every
                # platform — Mosaic lowering on TPU, and on the CPU proxy
                # _run pairs it with engine.interpret=true so the headline
                # executes the SAME ragged kernel body through the Pallas
                # interpreter (never bare Mosaic off-TPU, which a pinned
                # MCPX_BENCH_MODEL=2b with its lane-aligned head_dim 256
                # would otherwise attempt after the _device_guard CPU
                # fallback). MCPX_BENCH_PALLAS=0 restores the fused-jnp
                # reference on either platform: the smoke ladder uses it
                # to split "HBM OOM" from "first-ever hardware Mosaic
                # compile" at 2b startup, and it is the documented escape
                # hatch back to the (faster) r08-era CPU proxy basis.
                "use_pallas": _pallas_on(),
                # Headline-phase heterogeneous batching (the mixed phase
                # flips the flag per mode regardless): default off so the
                # headline numbers stay comparable to earlier rounds.
                # MCPX_BENCH_SPEC_HEADLINE implies it — the grammar-aware
                # drafter only runs in the heterogeneous slab.
                "hetero_batch": (
                    os.environ.get("MCPX_BENCH_HETERO", "0") == "1"
                    or os.environ.get("MCPX_BENCH_SPEC_HEADLINE", "0") == "1"
                ),
                # Headline-phase speculative decoding (the spec phase flips
                # it per mode regardless): default off, same comparability
                # argument.
                "speculative": {
                    "enabled": os.environ.get("MCPX_BENCH_SPEC_HEADLINE", "0")
                    == "1",
                    **(
                        {"k": int(os.environ["MCPX_BENCH_SPEC_K"])}
                        if "MCPX_BENCH_SPEC_K" in os.environ
                        else {}
                    ),
                },
                # Compile every (A, T) bucket before serving: the timed
                # region must contain zero XLA compiles. MCPX_BENCH_WARMUP=0
                # skips it for CPU smoke runs (a virtual-CPU fallback pays
                # minutes of compile for buckets it will never time fairly).
                "warmup_compile": os.environ.get("MCPX_BENCH_WARMUP", "1") != "0",
            },
            # Headline phases run tracing-DISABLED so the timed numbers stay
            # comparable to earlier rounds (and the acceptance criterion
            # "tracing off = no measurable regression" is the configuration
            # actually measured). The latency-attribution phase attaches its
            # own Tracer to the live control plane afterwards.
            "tracing": {"enabled": False},
            "planner": {
                "kind": "llm",
                # One constrained decode per plan; validation failures repair
                # via the heuristic (worst-case cost path for random weights).
                "max_plan_retries": 0,
                # 6-way shortlist keeps the compact BPE prompt inside the
                # 128-token prefill bucket.
                "shortlist_top_k": 6,
                # The in-run quality sample scores the model's RAW emissions
                # (same reasoning as planner/evaluate.py): serving-path edge
                # normalization would prune exactly the edges coherence
                # counts as incoherent, masking the nonsense this sample
                # exists to catch. Perf impact of the pass is host-side and
                # negligible, so the timed phases are unaffected either way.
                "prune_dataflow_free_edges": False,
            },
        }
    )


def _parse_prom(text: str) -> dict[str, float]:
    """Prometheus text exposition → {series_with_labels: value}."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = re.match(r"^(\S+?)(\{[^}]*\})?\s+([0-9.eE+-]+|NaN|Inf)$", line)
        if m:
            try:
                out[m.group(1) + (m.group(2) or "")] = float(m.group(3))
            except ValueError:
                pass
    return out


def _hist_quantile(
    prom: dict[str, float],
    name: str,
    q: float,
    prom_base: dict[str, float] | None = None,
    scale: float = 1e3,
) -> float:
    """Approximate quantile ``q`` from a histogram's cumulative buckets,
    linearly interpolated within the landing bucket. With ``prom_base``,
    buckets are delta'd so only observations between the two scrapes count
    (warmup must not contaminate the timed-phase split). ``scale`` converts
    bucket units to the reported unit (1e3 for seconds->ms histograms; 1.0
    for the ms-native ``mcpx_engine_hol_wait_ms``)."""
    buckets = []
    for k, v in prom.items():
        m = re.match(rf'^{re.escape(name)}_bucket\{{le="([^"]+)"\}}$', k)
        if m:
            le = float("inf") if m.group(1) == "+Inf" else float(m.group(1))
            buckets.append((le, v - (prom_base or {}).get(k, 0.0)))
    buckets.sort()
    total = buckets[-1][1] if buckets else 0
    if total <= 0:
        return 0.0
    target = total * q
    prev_le, prev_n = 0.0, 0.0
    for le, n in buckets:
        if n >= target:
            if le == float("inf"):
                return prev_le * scale
            frac = (target - prev_n) / max(1e-9, n - prev_n)
            return (prev_le + frac * (le - prev_le)) * scale
        prev_le, prev_n = le, n
    return 0.0


def _hist_p50(prom: dict[str, float], name: str, prom_base: dict[str, float] | None = None) -> float:
    """p50 (ms) of a seconds-bucketed histogram (see ``_hist_quantile``)."""
    return _hist_quantile(prom, name, 0.5, prom_base)


_TRAINED_CKPT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "mcpx", "models", "checkpoints", "planner_test_bpe.npz",
)


async def _run_quality_trained(
    n_intents: int = 48, deadline: "float | None" = None
) -> "dict | None":
    """Serve the committed TRAINED planner checkpoint (tiny model, BPE
    vocab) against its pinned eval protocol (registry size 1000, seed 0 —
    independent of MCPX_BENCH_SERVICES) and score plan quality — the
    semantic-capability number the headline run (random 2B-architecture
    weights) cannot produce (VERDICT r3 next #3). None when no checkpoint
    artifact is committed. Caveat: the checkpoint is trained on this
    synthetic registry's distribution (fresh intent draws, same services) —
    it measures the training+serving chain, not out-of-distribution
    generalisation."""
    ckpt = os.environ.get("MCPX_BENCH_QUALITY_CHECKPOINT", _TRAINED_CKPT)
    if not os.path.exists(ckpt):
        return None
    from mcpx.planner.evaluate import evaluate_planner

    # One shared eval protocol (CLI `mcpx eval-planner` uses the same):
    # registry size 1000 / seed 0 = the checkpoint's documented protocol
    # (ladder config6) — pinned regardless of MCPX_BENCH_SERVICES so an
    # off-default headline run cannot silently report an off-protocol
    # quality number under the same key (ADVICE r4). The protocol params
    # are echoed in the result so any override is visible.
    registry_size, registry_seed = 1000, 0
    # Same quantization as the headline serving config: the output JSON's
    # top-level "quantize" field must describe how the quality rows were
    # ACTUALLY served, not just how the timed phases were (ADVICE r5).
    quantize = os.environ.get("MCPX_BENCH_QUANTIZE", "none")
    out = await evaluate_planner(
        checkpoint=ckpt,
        registry_size=registry_size,
        registry_seed=registry_seed,
        n_intents=n_intents,
        use_pallas=_pallas_on(),
        quantize=quantize,
    )
    out["registry_size"] = registry_size
    out["registry_seed"] = registry_seed
    # Second row: the shortlist serving tier, whose TYPED-dataflow grammar
    # makes incoherent edges unrepresentable (coherence is structural
    # there; coverage/node_f1 remain the model's own). Reported under its
    # own key so the pinned registry-tier protocol above stays comparable
    # across rounds. Best-effort with its own bound, clamped to finish
    # BEFORE the caller's deadline — an outer cancellation mid-tier2 would
    # discard the already-measured pinned row above.
    tier2 = float(os.environ.get("MCPX_BENCH_QUALITY_TIER2_S", "720"))
    if deadline is not None:
        tier2 = min(tier2, deadline - time.monotonic() - 30.0)
    if tier2 < 60.0:
        out["shortlist_typed"] = {"skipped": "quality budget exhausted by tier 1"}
        return out
    try:
        short = await asyncio.wait_for(
            evaluate_planner(
                checkpoint=ckpt,
                registry_size=registry_size,
                registry_seed=registry_seed,
                n_intents=n_intents,
                use_pallas=_pallas_on(),
                constrain_names="shortlist",
                quantize=quantize,
            ),
            timeout=tier2,
        )
        out["shortlist_typed"] = {
            k: short[k]
            for k in (
                "coverage", "relevance", "coherence", "score", "node_f1", "llm_share",
            )
        }
    except Exception as e:  # noqa: BLE001 - auxiliary row only
        out["shortlist_typed"] = {"error": f"{type(e).__name__}: {e}"}
    return out


async def _overload_phase(cp, base: str, records, rng, plans_per_sec: float) -> "dict | None":
    """Scheduler overload scenario (ISSUE 1 acceptance): attach the
    SLO-aware admission scheduler (mcpx/scheduler/) to the LIVE server —
    the /plan handler reads ``cp.scheduler`` per request, so no second
    engine bring-up — and offer MCPX_BENCH_OVERLOAD_FACTOR (default 4x)
    the measured sustainable rate, open-loop. Reports shed-rate and
    degraded-share alongside the admitted-request latency so the headline
    JSON carries how the system DEGRADES, not just how fast it is when
    healthy. Runs after every headline scrape; detaches in a finally so
    the pass-through path is restored whatever happens. Skip with
    MCPX_BENCH_OVERLOAD=0."""
    if os.environ.get("MCPX_BENCH_OVERLOAD", "1") == "0":
        return None
    from aiohttp import ClientSession

    from mcpx.core.config import SchedulerConfig
    from mcpx.scheduler import Scheduler
    from mcpx.utils.synth import intent_for

    factor = float(os.environ.get("MCPX_BENCH_OVERLOAD_FACTOR", "4"))
    n = int(os.environ.get("MCPX_BENCH_OVERLOAD_REQUESTS", "256"))
    slo_ms = float(os.environ.get("MCPX_BENCH_SLO_MS", "1000"))
    rate = max(1.0, plans_per_sec * factor)
    scfg = SchedulerConfig(
        enabled=True,
        slo_ms=slo_ms,
        # Every request carries the SLO as its deadline: queue ETA past it
        # sheds with 429 + Retry-After instead of serving a corpse.
        default_deadline_ms=slo_ms,
        # Far fewer dispatch slots than the engine slab: at 4x offered load
        # the backlog then forms in the SCHEDULER's queue (where waits are
        # observed and the ladder can act), not invisibly inside the
        # engine's own pending line — even when the measured sustainable
        # rate (the 4x base) came out noisy-low.
        max_parallel=max(4, cp.config.engine.max_batch_size // 8),
        max_queue_depth=max(64, int(rate)),
        # Engage the ladder early: the phase exists to demonstrate SLO
        # defense, not to ride out a borderline queue at 0.5x SLO waits.
        degrade_threshold=0.25,
        recover_threshold=0.1,
        # Overload is sustained by construction here; a short hold keeps
        # the phase from spending half its requests waiting out hysteresis,
        # and a fast EWMA engages the ladder within a few observations —
        # the phase is hundreds of requests, not a day of traffic, so the
        # transient before engagement must not dominate the sample.
        degrade_min_hold_s=0.5,
        ewma_alpha=0.5,
    )
    engine = getattr(cp.planner, "engine", None)
    cp.scheduler = Scheduler(
        scfg,
        cp.metrics,
        engine_stats=engine.queue_stats if engine is not None else None,
    )
    # The engine's service-time EWMA (the deadline gate's floor) smooths at
    # config.scheduler.ewma_alpha — swap the live config section so both
    # estimators react at the phase's configured speed; restored below.
    prev_scfg = cp.config.scheduler
    cp.config.scheduler = scfg
    lat_by_tier: dict[str, list[float]] = {"admitted": [], "degraded": []}
    outcomes = {"admitted": 0, "degraded": 0, "shed": 0, "error": 0}
    try:
        from aiohttp import TCPConnector

        # Unlimited connector: at 4x offered load hundreds of requests are
        # legitimately in flight — aiohttp's default 100-connection pool
        # would throttle the offered load client-side and bill pool wait
        # to the server's latency numbers.
        async with ClientSession(connector=TCPConnector(limit=0)) as session:

            async def one(intent: str, delay: float) -> None:
                await asyncio.sleep(delay)
                t0 = time.monotonic()
                try:
                    async with session.post(
                        f"{base}/plan", json={"intent": intent}
                    ) as resp:
                        body = await resp.json()
                        status = resp.status
                except Exception:  # noqa: BLE001 - counted, not fatal
                    outcomes["error"] += 1
                    return
                ms = (time.monotonic() - t0) * 1e3
                if status == 200:
                    tier = "degraded" if body.get("planner") == "degraded" else "admitted"
                    outcomes[tier] += 1
                    lat_by_tier[tier].append(ms)
                elif status == 429:
                    outcomes["shed"] += 1
                else:
                    outcomes["error"] += 1

            intents = [f"{intent_for(records, rng)} [ovl{i}]" for i in range(n)]
            await asyncio.gather(*(one(x, i / rate) for i, x in enumerate(intents)))
    finally:
        cp.scheduler = None
        cp.config.scheduler = prev_scfg
    served = outcomes["admitted"] + outcomes["degraded"]
    lat_served = sorted(lat_by_tier["admitted"] + lat_by_tier["degraded"])

    # None, not NaN, for empty tiers: json.dumps would emit bare NaN —
    # invalid JSON to strict consumers of the one line this bench prints.
    def p50(xs: list[float]) -> "float | None":
        return round(statistics.median(xs), 1) if xs else None

    served_p50 = p50(lat_served)
    return {
        "offered_rate": round(rate, 2),
        "factor": factor,
        "requests": n,
        "slo_ms": slo_ms,
        **outcomes,
        "shed_rate": round(outcomes["shed"] / max(1, n), 4),
        "degraded_share": round(outcomes["degraded"] / max(1, served), 4),
        # All 200s, both tiers — what an accepted caller experienced.
        # Degraded serving IS the mechanism that keeps this inside the SLO
        # under overload, so within_slo is a claim about accepted requests
        # as a population, not about the LLM tier. null when nothing was
        # served at all (everything shed/errored).
        "served_p50_ms": served_p50,
        "served_p99_ms": (
            round(lat_served[int(0.99 * (len(lat_served) - 1))], 1)
            if lat_served
            else None
        ),
        "within_slo": bool(served_p50 <= slo_ms) if served_p50 is not None else None,
        # Per-tier split + its own SLO verdict, so a degraded-dominated run
        # is legible as such: primary_within_slo says whether LLM-served
        # requests themselves met the SLO (null when none were).
        "primary_p50_ms": p50(lat_by_tier["admitted"]),
        "degraded_p50_ms": p50(lat_by_tier["degraded"]),
        "primary_within_slo": (
            bool(p50(lat_by_tier["admitted"]) <= slo_ms)
            if lat_by_tier["admitted"]
            else None
        ),
    }


async def _mixed_phase(cp, overload: "dict | None") -> "dict | None":
    """Heterogeneous-batching scenario (ISSUE 3 acceptance): offer the
    ENGINE a steady mixed stream — grammar-constrained next to free-form,
    two temperatures, two grammars — closed-loop, and serve it twice at the
    same offered load: once with ``hetero_batch`` on (per-row sampling +
    stacked DFAs, strict queue-order admission) and once off (the
    homogeneous slab whose drain-to-switch ping-pongs the batch between
    configurations). Direct ``engine.generate`` calls: the /plan HTTP path
    pins one sampling config, and this phase exists to measure the mix.
    The flag flips on the LIVE engine between modes (both executables
    coexist; the flip happens only while the slab is idle, and each mode
    gets an untimed warm round so no XLA compile lands in its timed
    region). Reports ``mixed_plans_per_sec`` per mode, the speedup, the
    head-of-line wait p99 scraped from ``mcpx_engine_hol_wait_ms``, and
    echoes the overload phase's ``degraded_share`` so the three
    degradation-facing numbers sit together. Skip with MCPX_BENCH_MIXED=0."""
    if os.environ.get("MCPX_BENCH_MIXED", "1") == "0":
        return None
    engine = getattr(cp.planner, "engine", None)
    if engine is None or engine.state != "ready":
        return None
    from mcpx.planner.grammar import build_plan_grammar

    n = int(os.environ.get("MCPX_BENCH_MIXED_REQUESTS", "96"))
    hot = float(os.environ.get("MCPX_BENCH_MIXED_TEMPERATURE", "0.7"))
    tok = engine.tokenizer
    ecfg = engine.config.engine
    concurrency = min(2 * ecfg.max_batch_size, 64)
    budget = max(8, min(24, ecfg.max_decode_len))
    g_alt = build_plan_grammar(
        tok, ["mixed-rank-svc", "mixed-sum-svc", "mixed-etl-svc"]
    )
    # (constrained, temperature, grammar): the interleave a real control
    # plane serves — greedy /plan, sampled free-form, a second grammar,
    # sampled /plan. Round-robin so every slab admission sees the mix.
    classes = [
        (True, 0.0, None),
        (False, hot, None),
        (True, 0.0, g_alt),
        (True, hot, None),
        (False, 0.0, None),
    ]

    async def _idle() -> None:
        while engine._slab.n_active or engine._queue.qsize():
            await asyncio.sleep(0.05)
        await asyncio.sleep(0.1)

    async def one(i: int, sem: asyncio.Semaphore) -> None:
        constrained, temp, grammar = classes[i % len(classes)]
        prompt = tok.encode(f"mixed intent {i}: compose the services. JSON:")
        async with sem:
            await engine.generate(
                prompt,
                max_new_tokens=budget,
                constrained=constrained,
                temperature=temp,
                grammar=grammar,
            )

    async def run_mode(hetero: bool) -> dict:
        await _idle()
        ecfg.hetero_batch = hetero
        # Untimed warm round at the SAME concurrency as the timed run: the
        # first timed admission drains up to `concurrency` pending requests
        # into one cohort, so warming with fewer would leave that cohort's
        # (A, T) admit executables to compile INSIDE the timed region and
        # contaminate mixed_plans_per_sec/HoL for whichever mode ran first.
        n_warm = max(len(classes), concurrency)
        warm_sem = asyncio.Semaphore(concurrency)
        await asyncio.gather(*(one(i, warm_sem) for i in range(n_warm)))
        await _idle()
        prom0 = _parse_prom(cp.metrics.render().decode())
        sem = asyncio.Semaphore(concurrency)
        t0 = time.monotonic()
        await asyncio.gather(*(one(i, sem) for i in range(n)))
        elapsed = time.monotonic() - t0
        prom1 = _parse_prom(cp.metrics.render().decode())
        return {
            "mixed_plans_per_sec": round(n / max(1e-9, elapsed), 2),
            "hol_p99_ms": round(
                _hist_quantile(
                    prom1, "mcpx_engine_hol_wait_ms", 0.99, prom0, scale=1.0
                ),
                1,
            ),
            "hol_p50_ms": round(
                _hist_quantile(
                    prom1, "mcpx_engine_hol_wait_ms", 0.5, prom0, scale=1.0
                ),
                1,
            ),
        }

    prev = ecfg.hetero_batch
    try:
        drain = await run_mode(False)
        hetero = await run_mode(True)
    finally:
        await _idle()
        ecfg.hetero_batch = prev
    return {
        "requests": n,
        "concurrency": concurrency,
        "classes": len(classes),
        "hot_temperature": hot,
        "hetero": hetero,
        "drain": drain,
        "speedup": round(
            hetero["mixed_plans_per_sec"] / max(1e-9, drain["mixed_plans_per_sec"]),
            3,
        ),
        # The scheduler-overload degradation share, echoed so the three
        # degradation-facing numbers (mixed throughput, HoL wait, degraded
        # share) read together in one place.
        "degraded_share": overload.get("degraded_share") if overload else None,
    }


async def _spec_phase(cp) -> "dict | None":
    """Grammar-aware speculative decoding scenario (ISSUE 6 acceptance):
    offer the ENGINE the same mixed stream twice at the same offered load —

      - **off**: a true per-token baseline. ``speculative.enabled=false``
        AND ``speculate_k=1``, so DFA fast-forward is disabled too: every
        emitted token costs one full model forward (the per-token host/
        device loop speculation exists to kill — also the bug class the
        ``per-token-host-loop`` lint rule polices on the host side). The
        fast-forward (``speculate_k``, default 8) is deliberately OFF in
        the baseline because it is itself a grammar-only speculation
        mechanism — leaving it on would measure speculation against
        speculation; the ``speculative.draft="grammar"`` ablation is the
        in-design-space equivalent of that comparison.
      - **on**: the recurrent drafter + grammar pre-filter + one batched
        ``[rows, K+1]`` verify (``EngineConfig.speculative``).

    Both modes serve a DEDICATED single-device engine (explicit 1×1 mesh,
    same model/vocab/page geometry as the serving engine, hetero slab on):
    speculation changes PER-CHIP decode economics — tokens per forward on
    one accelerator — and that is what this phase isolates. On the
    CPU-fallback platform the serving engine's 8-way *virtual* mesh
    serializes every shard and collective onto the same host cores, a
    simulation artifact whose per-forward cost no real single-chip (or
    per-chip TPU) deployment pays; measuring the OFF→ON delta under it
    would attribute fake collective overhead to speculation. Direct
    ``engine.generate`` calls like the mixed phase (this measures the
    decode loop, not HTTP); each mode gets an untimed warm round so no XLA
    compile lands in its timed region, the two modes are timed in
    interleaved rounds so a co-tenant CPU burst cannot land entirely
    inside one mode's window, and the serving engine sits idle throughout
    (the shared metrics registry deltas are the spec engine's alone).
    Reports per-mode ``decode_tok_s``/``tok_per_forward``; the headline
    ``spec_speedup`` is the ON/OFF **tokens-per-forward ratio** (on
    bandwidth-bound accelerator decode a [rows, K+1] window streams the
    weights once, so tokens-per-forward IS the wall speedup — the CPU
    proxy's FLOP-bound forward cost and co-tenant core availability make
    its wall clock a measure of the neighbours; that ratio is still
    reported as ``spec_wall_speedup``); plus the accept rate overall and
    split by constrained-vs-free row class (scraped from
    ``mcpx_engine_spec_{drafted,accepted}_total``), and verifies the
    deterministic (greedy) rows' outputs are byte-identical across modes —
    speculation must be a pure perf lever, never a quality one (a parity
    break fails the bench). Skip with MCPX_BENCH_SPEC=0."""
    raw_gate = os.environ.get("MCPX_BENCH_SPEC", "1")
    if raw_gate not in ("0", "1"):
        # This name used to be the fast-forward-width lever (now
        # MCPX_BENCH_SPECULATE_K): a leftover numeric value from an old
        # harness would silently lose its tuning AND silently enable this
        # phase — say so instead.
        print(
            f"bench: MCPX_BENCH_SPEC={raw_gate!r} is now the spec-phase "
            "on/off gate (0|1); the speculate_k lever moved to "
            "MCPX_BENCH_SPECULATE_K",
            file=sys.stderr,
        )
    if raw_gate == "0":
        return None
    serving = getattr(cp.planner, "engine", None)
    if serving is None or serving.state != "ready":
        return None
    from mcpx.core.config import MCPXConfig
    from mcpx.engine.engine import InferenceEngine
    from mcpx.planner.grammar import build_plan_grammar

    n = max(1, int(os.environ.get("MCPX_BENCH_SPEC_REQUESTS", "192")))
    hot = float(os.environ.get("MCPX_BENCH_MIXED_TEMPERATURE", "0.7"))
    spec_dict = serving.config.to_dict()
    spec_dict["engine"]["data_axis"] = 1
    spec_dict["engine"]["model_axis"] = 1
    spec_dict["engine"]["hetero_batch"] = True
    spec_dict["engine"]["warmup_compile"] = False
    # Eager admission: a speculated row retires in a handful of windows, so
    # the default small-cohort rate limit leaves the slab half-empty
    # between admit waves (measured: ON-mode occupancy 0.5 vs 0.88 OFF) —
    # a scheduling artifact that would be billed to speculation. Applies
    # to both modes equally.
    spec_dict["engine"]["admit_min_free"] = 1
    spec_dict["engine"]["admit_max_wait_s"] = 0.0
    engine = InferenceEngine(MCPXConfig.from_dict(spec_dict), metrics=cp.metrics)
    await engine.start()
    tok = engine.tokenizer
    ecfg = engine.config.engine
    concurrency = min(2 * ecfg.max_batch_size, 64)
    # Full-size plans (BPE teacher plans run ~43 tokens, p99 53 — see
    # _build_config): a clipped 24-token budget retires rows so fast the
    # slab drains between admissions, and the phase should be decode-
    # dominated anyway.
    budget = max(8, min(48, ecfg.max_decode_len))
    g_alt = build_plan_grammar(
        tok, ["spec-rank-svc", "spec-sum-svc", "spec-etl-svc"]
    )
    # The serving mix: greedy /plan (the common case speculation targets),
    # a second grammar, free-form greedy, and two hot rows so stochastic
    # accept rules run in the same slab.
    classes = [
        (True, 0.0, None),
        (True, 0.0, g_alt),
        (False, 0.0, None),
        (True, hot, None),
        (False, hot, None),
    ]
    deterministic = {i for i, c in enumerate(classes) if c[1] <= 0.0}

    async def _idle() -> None:
        while engine._slab.n_active or engine._queue.qsize():
            await asyncio.sleep(0.05)
        await asyncio.sleep(0.1)

    async def one(i: int, sem: asyncio.Semaphore, sink: "dict | None") -> None:
        constrained, temp, grammar = classes[i % len(classes)]
        prompt = tok.encode(f"spec intent {i}: compose the services. JSON:")
        async with sem:
            r = await engine.generate(
                prompt,
                max_new_tokens=budget,
                constrained=constrained,
                temperature=temp,
                grammar=grammar,
            )
        if sink is not None and (i % len(classes)) in deterministic:
            sink[i] = r.token_ids

    def _rate(prom1, prom0, cls):
        dr = prom1.get(
            f'mcpx_engine_spec_drafted_total{{cls="{cls}"}}', 0.0
        ) - prom0.get(f'mcpx_engine_spec_drafted_total{{cls="{cls}"}}', 0.0)
        ac = prom1.get(
            f'mcpx_engine_spec_accepted_total{{cls="{cls}"}}', 0.0
        ) - prom0.get(f'mcpx_engine_spec_accepted_total{{cls="{cls}"}}', 0.0)
        return dr, ac

    # OFF and ON are timed in INTERLEAVED rounds, not one solid block per
    # mode, and each mode reports its BEST round: on a small shared-core
    # host a co-tenant burst that lands inside one mode's only timed
    # window can swing the ratio by 3x+ in either direction (measured —
    # and contention hits the modes asymmetrically: ON's [rows, K+1]
    # verify forwards are compute-heavy where OFF is dispatch-overhead-
    # bound). External load only ever SLOWS a round, so the per-mode best
    # round estimates each mode's uncontended rate; a burst now has to
    # poison every round of a mode, not one block, to skew the headline.
    # Counters (tokens/forwards/accepts) still total across rounds.
    ROUNDS = 3
    # Every timed chunk offers its whole request set at once, and the
    # closed-loop concurrency never exceeds the chunk: slab occupancy —
    # which the ON mode's per-row verify window amortises over — is then
    # identical across rounds and modes instead of degrading when a chunk
    # is smaller than the semaphore.
    chunk_n = max(1, n // ROUNDS)
    concurrency = min(concurrency, chunk_n)
    acc = {
        m: {"tok": 0.0, "fwd": 0.0, "elapsed": 0.0, "spec": [0.0] * 4,
            "rounds": []}
        for m in (False, True)
    }
    sinks: dict = {False: {}, True: {}}
    warmed = {False: False, True: False}
    prev_speculate_k = ecfg.speculate_k

    async def set_mode(spec_on: bool) -> None:
        await _idle()  # the spec latch flips only on an empty slab
        ecfg.speculative.enabled = spec_on
        ecfg.speculate_k = prev_speculate_k if spec_on else 1
        if not warmed[spec_on]:  # keep each mode's XLA compile untimed
            n_warm = max(len(classes), concurrency)
            warm_sem = asyncio.Semaphore(concurrency)
            # Warm ids DISJOINT from the timed ranges: warm requests must
            # not pre-build any per-prompt engine state (prefixes, pages)
            # a timed round then reuses.
            await asyncio.gather(
                *(one(1_000_000 + i, warm_sem, None) for i in range(n_warm))
            )
            await _idle()
            warmed[spec_on] = True

    try:
        for r in range(ROUNDS):
            lo, hi = r * n // ROUNDS, (r + 1) * n // ROUNDS
            if lo >= hi:
                continue
            for spec_on in (False, True):
                await set_mode(spec_on)
                prom0 = _parse_prom(cp.metrics.render().decode())
                sem = asyncio.Semaphore(concurrency)
                t0 = time.monotonic()
                await asyncio.gather(
                    *(one(i, sem, sinks[spec_on]) for i in range(lo, hi))
                )
                elapsed = time.monotonic() - t0
                prom1 = _parse_prom(cp.metrics.render().decode())
                a = acc[spec_on]
                r_tok = prom1.get(
                    "mcpx_engine_decode_tokens_total", 0.0
                ) - prom0.get("mcpx_engine_decode_tokens_total", 0.0)
                a["tok"] += r_tok
                a["fwd"] += prom1.get(
                    "mcpx_engine_decode_forwards_total", 0.0
                ) - prom0.get("mcpx_engine_decode_forwards_total", 0.0)
                a["elapsed"] += elapsed
                a["rounds"].append(
                    {
                        "decode_tok_s": round(r_tok / max(1e-9, elapsed), 1),
                        "plans_per_sec": round(
                            (hi - lo) / max(1e-9, elapsed), 2
                        ),
                    }
                )
                if spec_on:
                    dr_c, ac_c = _rate(prom1, prom0, "constrained")
                    dr_f, ac_f = _rate(prom1, prom0, "free")
                    a["spec"] = [
                        x + y for x, y in zip(a["spec"], (dr_c, ac_c, dr_f, ac_f))
                    ]
    finally:
        await engine.aclose()

    def mode_res(spec_on: bool) -> dict:
        a = acc[spec_on]
        res = {
            "decode_tok_s": max(r["decode_tok_s"] for r in a["rounds"]),
            "tok_per_forward": round(a["tok"] / max(1.0, a["fwd"]), 2),
            "plans_per_sec": max(r["plans_per_sec"] for r in a["rounds"]),
            "rounds": a["rounds"],
        }
        if spec_on:
            dr_c, ac_c, dr_f, ac_f = a["spec"]
            res["accept_rate"] = {
                "overall": round((ac_c + ac_f) / max(1.0, dr_c + dr_f), 4),
                "constrained": round(ac_c / max(1.0, dr_c), 4),
                "free": round(ac_f / max(1.0, dr_f), 4),
                "drafted": int(dr_c + dr_f),
                "accepted": int(ac_c + ac_f),
            }
        return res

    off, on = mode_res(False), mode_res(True)
    out_off, out_on = sinks[False], sinks[True]
    # Byte-identical greedy outputs across modes: the phase's own honesty
    # gate — a "speedup" that changes what greedy rows emit is a bug, not
    # a win (the same invariant tests/test_speculative.py pins), so it
    # FAILS the bench like every other honesty gate rather than burying a
    # false flag under a passing headline.
    broken = [i for i in out_off if out_on.get(i) != out_off[i]]
    if broken:
        raise BenchGateError(
            f"speculation changed greedy outputs on {len(broken)}/"
            f"{len(out_off)} deterministic rows (spec-on vs spec-off)"
        )
    return {
        "requests": n,
        "concurrency": concurrency,
        "k": ecfg.speculative.k,
        "draft": ecfg.speculative.draft,
        # The baseline is one-forward-per-token: speculate_k fast-forward
        # (itself grammar-only speculation) is disabled in OFF, not just
        # the drafter — see the phase docstring.
        "off_basis": "per_token",
        "off": off,
        "on": on,
        "spec_decode_tok_s": on["decode_tok_s"],
        # The headline speedup is the FORWARD-AMORTISATION ratio — decode
        # tokens per model forward, ON over OFF. On accelerator decode the
        # forward is HBM-bandwidth-bound: a [rows, K+1] verify window
        # streams the weights exactly once, so a window forward costs what
        # a single-token forward costs and tokens-per-forward IS the
        # wall-clock decode speedup. The CPU proxy's forward is FLOP-bound
        # instead (a W-wide window really does ~W× the arithmetic) AND its
        # wall clock moves 3x+ with co-tenant core availability (measured:
        # identical code, 0.9-3.5 wall ratios across a day) — gating on it
        # would measure the neighbours, not the subsystem. The wall-clock
        # ratio is still reported right below, flagged by basis.
        "spec_speedup": round(
            on["tok_per_forward"] / max(1e-9, off["tok_per_forward"]), 3
        ),
        "spec_speedup_basis": "tok_per_forward",
        "spec_wall_speedup": round(
            on["decode_tok_s"] / max(1e-9, off["decode_tok_s"]), 3
        ),
        "spec_accept_rate": on.get("accept_rate"),
        "greedy_parity": True,  # gated above: a parity break raised
    }


async def _prefix_phase(cp) -> "dict | None":
    """Radix prefix KV reuse scenario (ISSUE 8 acceptance): the SAME
    repeat-heavy intent stream planned twice at the same offered load —

      - **off**: ``engine.prefix_cache=false`` — every /plan re-prefills
        its whole prompt (header + registry shortlist + intent), the
        pre-radix baseline.
      - **on**: the radix tree matches each prompt's resident head, pins
        it, and prefills only the unmatched suffix; the page-aligned
        remainder is inserted back for the next sharer.

    Direct ``cp.plan(use_cache=False)`` calls (the PLAN cache would
    short-circuit the repeats this phase exists to measure; prefix reuse
    is the engine-level answer for exactly the traffic the plan cache
    can't serve — per-request decode with shared prompt heads). Reports
    ``prefill_tokens_per_request`` per mode (engine counter deltas — the
    prefix build's own tokens are billed by the engine, so the ON number
    is honest amortisation, not hidden cost), the request- and
    token-level ``prefix_hit_rate``, and COLD vs WARM replan p50: a
    replan prompt re-rendered over the original service order with the
    exclusions spliced into the suffix (Avoid line) continues from the
    cached prefix at incremental-decode cost, vs the prefix-off cold
    re-plan. The flip is admission-scoped (no executable or page-slack
    geometry depends on it), so a live engine serves both modes; each
    mode idles the slab first. Skip with MCPX_BENCH_PREFIX=0."""
    if os.environ.get("MCPX_BENCH_PREFIX", "1") == "0":
        return None
    engine = getattr(cp.planner, "engine", None)
    if engine is None or engine.state != "ready":
        return None
    import random as _random

    from mcpx.utils.synth import intent_for

    ecfg = engine.config.engine
    records = await cp.registry.list_services()
    rng = _random.Random(23)
    n_unique = max(1, int(os.environ.get("MCPX_BENCH_PREFIX_INTENTS", "8")))
    reps = max(2, int(os.environ.get("MCPX_BENCH_PREFIX_REPS", "8")))
    n_replans = max(1, int(os.environ.get("MCPX_BENCH_PREFIX_REPLANS", "6")))
    pool = [f"{intent_for(records, rng)} [pfx{i}]" for i in range(n_unique)]
    intents = [pool[i % n_unique] for i in range(n_unique * reps)]
    concurrency = min(engine.config.engine.max_batch_size, 16)

    async def _idle() -> None:
        while engine._slab.n_active or engine._queue.qsize():
            await asyncio.sleep(0.05)
        await asyncio.sleep(0.1)

    def _prom() -> dict:
        return _parse_prom(cp.metrics.render().decode())

    prev_on = ecfg.prefix_cache

    async def measure(on: bool) -> dict:
        await _idle()
        ecfg.prefix_cache = on
        prom0 = _prom()
        sem = asyncio.Semaphore(concurrency)

        async def one(intent: str) -> None:
            async with sem:
                await cp.plan(intent, use_cache=False)

        t0 = time.monotonic()
        await asyncio.gather(*(one(i) for i in intents))
        await _idle()
        elapsed = time.monotonic() - t0
        prom1 = _prom()

        def d(name: str) -> float:
            return prom1.get(name, 0.0) - prom0.get(name, 0.0)

        n = len(intents)
        hits = d("mcpx_kv_prefix_hits_total")
        misses = d("mcpx_kv_prefix_misses_total")
        matched = d("mcpx_kv_prefix_matched_tokens_total")
        prefilled = d("mcpx_engine_prefill_tokens_total")
        res = {
            "requests": n,
            "plans_per_sec": round(n / max(1e-9, elapsed), 2),
            "prefill_tokens_per_request": round(prefilled / max(1, n), 1),
        }
        if on:
            res["prefix_hit_rate"] = round(hits / max(1.0, hits + misses), 4)
            res["prefix_token_hit_rate"] = round(
                matched / max(1.0, matched + prefilled), 4
            )
            res["prefix_shared_pages"] = int(
                prom1.get("mcpx_kv_prefix_shared_pages", 0.0)
            )
        return res

    async def timed_replan(intent: str, on: bool) -> "tuple[float, float] | None":
        """One replan sample (the planner call plan_and_execute makes
        after a node failure): plan, exclude the first service, re-plan
        with the prior order threaded through. Returns (wall_ms, global
        prefill-counter delta over the timed call) — None when the plan
        came back empty."""
        plan, _ = await cp.plan(intent, use_cache=False)
        if not plan.nodes:
            return None
        exclude = {plan.nodes[0].service}
        prior = (
            tuple(plan.prompt_services)
            if on and plan.prompt_services
            else None
        )
        ctx = await cp._context(intent, exclude, replan_prior=prior)
        pf0 = _prom().get("mcpx_engine_prefill_tokens_total", 0.0)
        t0 = time.monotonic()
        await cp.planner.plan(intent, ctx)
        lat_ms = (time.monotonic() - t0) * 1e3
        return lat_ms, _prom().get("mcpx_engine_prefill_tokens_total", 0.0) - pf0

    async def replan_probe(on: bool) -> "dict | None":
        """Quiet-slab replan cost: warm replans render over the original
        service order with an Avoid suffix and continue from the cached
        prefix; cold replans re-prefill everything. Reports wall p50 AND
        the replan's own prefill bill — nothing else runs, so the global
        prefill delta IS the replan's (the mechanism's direct effect; on
        a decode-dominated proxy the wall ratio understates it)."""
        await _idle()
        ecfg.prefix_cache = on
        lats: list[float] = []
        prefilled = 0.0
        for i in range(n_replans):
            sample = await timed_replan(pool[i % n_unique], on)
            if sample is None:
                continue
            lats.append(sample[0])
            prefilled += sample[1]
        if not lats:
            return None
        return {
            "p50_ms": round(statistics.median(lats), 1),
            "prefill_tokens": round(prefilled / len(lats), 1),
        }

    async def sat_replan_probe() -> "dict | None":
        """Warm replans AT SATURATION (the r06 weakness): the same warm
        replan measured while background cache-busting plan traffic keeps
        the slab full — so the replan's suffix decode contends with
        admission cohorts and its cached prefix with eviction pressure.
        Background pumps stream unique intents at slab concurrency; only
        the replan planner call is timed. Skip with MCPX_BENCH_PREFIX_SAT=0."""
        if os.environ.get("MCPX_BENCH_PREFIX_SAT", "1") == "0":
            return None
        await _idle()
        ecfg.prefix_cache = True
        stop = asyncio.Event()
        pumped = {"n": 0}

        async def pump(worker_id: int) -> None:
            j = 0
            while not stop.is_set():
                j += 1
                try:
                    await cp.plan(
                        f"{pool[j % n_unique]} sat{worker_id}-{j}",
                        use_cache=False,
                    )
                except Exception:  # noqa: BLE001 - saturation pressure, not the measurement
                    if stop.is_set():
                        return
                else:
                    # Failed pumps (shed, queue-full under the induced
                    # saturation) exert no slab pressure — counting them
                    # would overstate background_plans_per_sec.
                    pumped["n"] += 1

        pumps = [
            asyncio.create_task(pump(w)) for w in range(concurrency)
        ]
        lats: list[float] = []
        prefilled = 0.0
        t_win0 = time.monotonic()
        try:
            # Let the pumps actually saturate the slab before measuring.
            await asyncio.sleep(0.3)
            for i in range(n_replans):
                try:
                    sample = await timed_replan(pool[i % n_unique], True)
                except Exception:  # noqa: BLE001 - the same shed/queue-full the pumps induce can hit a timed replan; drop the sample, keep the probe (and the run) alive
                    continue
                if sample is None:
                    continue
                lats.append(sample[0])
                prefilled += sample[1]
        finally:
            stop.set()
            await asyncio.gather(*pumps, return_exceptions=True)
        window_s = time.monotonic() - t_win0
        await _idle()
        if not lats:
            return None
        return {
            "p50_ms": round(statistics.median(lats), 1),
            "replans": len(lats),
            # GLOBAL prefill tokens per timed-replan window: the counter
            # delta includes the concurrent pumps' prefills, so this is
            # the prefill pressure the replan contended with — NOT the
            # replan's own bill (the quiet probes report that cleanly).
            "window_prefill_tokens": round(prefilled / len(lats), 1),
            "background_plans_per_sec": round(
                pumped["n"] / max(1e-9, window_s), 2
            ),
            "background_concurrency": concurrency,
        }

    try:
        off = await measure(False)
        cold = await replan_probe(False)
        on = await measure(True)
        warm = await replan_probe(True)
        warm_sat = await sat_replan_probe()
    finally:
        ecfg.prefix_cache = prev_on
    cold_p50 = cold["p50_ms"] if cold else None
    warm_p50 = warm["p50_ms"] if warm else None
    out = {
        "requests": len(intents),
        "unique_intents": n_unique,
        "off": off,
        "on": on,
        "prefill_tokens_per_request": on["prefill_tokens_per_request"],
        "prefill_reduction": round(
            off["prefill_tokens_per_request"]
            / max(1e-9, on["prefill_tokens_per_request"]),
            2,
        ),
        "prefix_hit_rate": on.get("prefix_hit_rate"),
        "prefix_token_hit_rate": on.get("prefix_token_hit_rate"),
        "replan_p50_cold_ms": cold_p50,
        "replan_p50_warm_ms": warm_p50,
        # Warm replans measured while background traffic saturates the
        # slab (the r06-surfaced weakness, now a tracked number).
        "sat": warm_sat,
        "replan_warm_sat_p50_ms": warm_sat["p50_ms"] if warm_sat else None,
        "replan_speedup": (
            round(cold_p50 / warm_p50, 2)
            if cold_p50 and warm_p50
            else None
        ),
        # The mechanism's direct effect, independent of decode share:
        # prompt tokens each replan actually re-prefilled.
        "replan_prefill_tokens_cold": cold["prefill_tokens"] if cold else None,
        "replan_prefill_tokens_warm": warm["prefill_tokens"] if warm else None,
    }
    return out


async def _tier_phase(cp) -> "dict | None":
    """Tiered KV cache scenario (ISSUE 11 acceptance): drive a working set
    >= 10x the HBM-resident radix cap through DEDICATED small engines
    (same model/vocab as the serving engine, explicit 1x1 mesh, tiny page
    pool so the cap is cheap to overflow) and compare

      - **single**: ``kv_tier`` off — eviction destroys refcount-0
        subtrees, so round 2+ of the stream re-prefills almost everything
        (the cliff).
      - **tiered**: evicted runs spill to pinned host RAM and re-admit by
        async page copy on match — the token hit rate holds (the slope).

    Then three sub-probes on the tiered configuration: an ADVERSARIAL
    THRASH tenant (unique prompts at volume) against a repeat-heavy victim
    tenant — the governor's weighted-fair quotas keep the victim's token
    hit rate at its floor; a WARM RESTART (clean aclose writes the KV
    snapshot, a successor engine restores it into the host tier and serves
    its first plan from re-admitted KV — first-plan prefill tokens vs the
    cold engine's); and a CHAOS round (seeded SpillChaos: host-alloc
    failures + copy-latency spikes) proving the degradation paths serve
    correctly and count visibly. Greedy outputs are asserted byte-identical
    tiered-vs-single (tier off is a pass-through, never a quality lever —
    a parity break fails the bench). Direct ``engine.generate`` with
    synthetic token-id prompts: this measures the cache machinery, not
    planning. Skip with MCPX_BENCH_TIER=0."""
    if os.environ.get("MCPX_BENCH_TIER", "1") == "0":
        return None
    serving = getattr(cp.planner, "engine", None)
    if serving is None or serving.state != "ready":
        return None
    import tempfile

    from mcpx.core.config import MCPXConfig
    from mcpx.engine.engine import InferenceEngine

    n_prompts = max(8, int(os.environ.get("MCPX_BENCH_TIER_PROMPTS", "64")))
    rounds = max(2, int(os.environ.get("MCPX_BENCH_TIER_ROUNDS", "3")))
    snap_dir = tempfile.mkdtemp(prefix="mcpx-tier-")
    snap = os.path.join(snap_dir, "kv.snap")

    def tier_cfg(enabled: bool, *, chaos: str = "", snapshot: str = ""):
        d = serving.config.to_dict()
        d["engine"].update(
            {
                "data_axis": 1,
                "model_axis": 1,
                "warmup_compile": False,
                "hetero_batch": False,
                "max_batch_size": 4,
                "max_pages_per_seq": 16,
                "kv_page_size": 16,
                "max_decode_len": 8,
                "prefix_cache": True,
                "prefix_cache_entries": 4096,
            }
        )
        d["engine"]["speculative"] = {"enabled": False}
        d["engine"]["kv_tier"] = {
            "enabled": enabled,
            "host_mb": 256.0,
            "copy_tokens_per_cycle": 4096,
            "snapshot_path": snapshot,
            "chaos_profile": chaos,
        }
        return MCPXConfig.from_dict(d)

    async def idle(engine) -> None:
        while engine._slab.n_active or engine._queue.qsize():
            await asyncio.sleep(0.02)
        await asyncio.sleep(0.05)

    def prom() -> dict:
        return _parse_prom(cp.metrics.render().decode())

    tok = serving.tokenizer
    prompts = [
        tok.encode(f"tier workload {i}: " + "compose rank fetch join " * 12)[:128]
        for i in range(n_prompts)
    ]
    # The resident device cap of the dedicated geometry — read off the
    # first constructed engine (run_mode below), never re-derived from
    # the config constants (a tier_cfg tune must not silently skew the
    # reported working_set_ratio).
    cap_tokens = 0
    working_set = sum(
        (len(p) // 16) * 16 for p in prompts
    )  # page-aligned cacheable tokens

    async def drive(engine, stream, *, tenants=None, sink=None) -> tuple[float, float]:
        """Returns (elapsed_s, first_request_ms) — the first-request wall
        is the cold/warm first-plan latency probe (symmetric: a fresh
        engine pays its first-dispatch compiles either way)."""
        t0 = time.monotonic()
        first_ms = 0.0
        for j, p in enumerate(stream):
            r = await engine.generate(
                p,
                max_new_tokens=2,
                constrained=False,
                temperature=0.0,
                tenant=(tenants[j] if tenants else "default"),
            )
            if j == 0:
                first_ms = (time.monotonic() - t0) * 1e3
            if sink is not None:
                sink.append(r.token_ids)
        await idle(engine)
        return time.monotonic() - t0, first_ms

    async def run_mode(enabled: bool, snapshot: str = "") -> tuple[dict, list, float]:
        nonlocal cap_tokens
        engine = InferenceEngine(
            tier_cfg(enabled, snapshot=snapshot), metrics=cp.metrics
        )
        await engine.start()
        cap_tokens = engine._prefix_cache.max_tokens
        outs: list = []
        p0 = prom()
        elapsed = 0.0
        first_ms = 0.0
        for rnd in range(rounds):
            dt, fms = await drive(
                engine, prompts, sink=(outs if rnd == 0 else None)
            )
            elapsed += dt
            if rnd == 0:
                first_ms = fms
        p1 = prom()
        prefilled = p1.get("mcpx_engine_prefill_tokens_total", 0.0) - p0.get(
            "mcpx_engine_prefill_tokens_total", 0.0
        )
        matched = p1.get("mcpx_kv_prefix_matched_tokens_total", 0.0) - p0.get(
            "mcpx_kv_prefix_matched_tokens_total", 0.0
        )
        st = engine.prefix_cache_stats()
        res = {
            # Matched vs PREFILLED (tokens actually paid for), not the
            # tree's matched-vs-inserted rate: the single-tier baseline
            # refuses inserts once full, which would hide every
            # re-prefilled token from an inserted-based denominator.
            "token_hit_rate": round(
                matched / max(1.0, matched + prefilled), 4
            ),
            "prefill_tokens_per_request": round(
                prefilled / (n_prompts * rounds), 1
            ),
            "plans_per_sec": round(n_prompts * rounds / max(1e-9, elapsed), 2),
        }
        if enabled:
            t = st["tier"]
            res.update(
                spills=t["spills"],
                readmits=t["readmits"],
                destructive_evictions=t["destructive_evictions"],
                host_tokens=t["host_tokens"],
            )
        else:
            res["evictions"] = st["evictions"]
        res["first_plan_ms"] = round(first_ms, 1)
        if not snapshot:
            await engine.aclose()
            return res, outs, (0.0, 0.0)
        # Clean close writes the snapshot; report first-plan prefill on
        # the SUCCESSOR (the warm-restart acceptance number).
        await engine.aclose()
        warm = InferenceEngine(tier_cfg(True, snapshot=snapshot), metrics=cp.metrics)
        await warm.start()
        wf0 = prom().get("mcpx_engine_prefill_tokens_total", 0.0)
        t0 = time.monotonic()
        r = await warm.generate(
            prompts[0], max_new_tokens=2, constrained=False, temperature=0.0
        )
        warm_ms = (time.monotonic() - t0) * 1e3
        await idle(warm)
        warm_prefill = prom().get("mcpx_engine_prefill_tokens_total", 0.0) - wf0
        if r.token_ids != outs[0]:
            await warm.aclose()
            raise BenchGateError(
                "warm-restart output diverged — snapshot KV must attend "
                "byte-identically to the run that wrote it"
            )
        await warm.aclose()
        return res, outs, (warm_prefill, warm_ms)

    import shutil

    try:
        return await _tier_phase_body(
            run_mode, drive, prom, prompts, n_prompts, rounds, snap,
            cap_getter=lambda: cap_tokens, working_set=working_set,
            tier_cfg=tier_cfg, cp=cp, tok=tok,
        )
    finally:
        shutil.rmtree(snap_dir, ignore_errors=True)


async def _tier_phase_body(
    run_mode, drive, prom, prompts, n_prompts, rounds, snap, *,
    cap_getter, working_set, tier_cfg, cp, tok,
):
    from mcpx.engine.engine import InferenceEngine

    # --- single-tier baseline.
    single, single_outs, _ = await run_mode(False)
    # The cold comparator for the warm-restart probe: a cold engine's
    # first plan prefills the whole (page-aligned) prompt — deterministic
    # for this geometry, measured identically by the baseline's round 1.
    cold_first = float((len(prompts[0]) // 16) * 16)

    # --- tiered + warm restart (same stream, same offered order).
    tiered, tiered_outs, (warm_first, warm_first_ms) = await run_mode(
        True, snapshot=snap
    )
    if tiered_outs != single_outs:
        raise BenchGateError(
            "tiered KV outputs diverged from single-tier on the greedy "
            "stream — the tier must be a pure residency lever"
        )

    # --- adversarial thrash tenant vs repeat-heavy victim (governed).
    gov_engine = InferenceEngine(tier_cfg(True), metrics=cp.metrics)
    await gov_engine.start()
    victim_set = prompts[:4]
    thrash_unique = [
        tok.encode(f"thrash {i}: " + "spam flood churn " * 14)[:128]
        for i in range(n_prompts * 2)
    ]
    # Interleave: every thrash burst is followed by the victim's repeats.
    stream: list = []
    tenants: list = []
    ti = 0
    for burst in range(rounds * 4):
        for _ in range(4):
            stream.append(thrash_unique[ti % len(thrash_unique)])
            tenants.append("thrash")
            ti += 1
        for v in victim_set:
            stream.append(v)
            tenants.append("victim")
    await drive(gov_engine, stream, tenants=tenants)
    gstats = gov_engine.prefix_cache_stats()["governor"] or {}
    victim_thr = (gstats.get("victim") or {}).get("token_hit_rate", 0.0)
    thrash_thr = (gstats.get("thrash") or {}).get("token_hit_rate", 0.0)
    await gov_engine.aclose()

    # --- chaos round: seeded faults on the copy paths; serving must stay
    # correct (greedy parity vs the clean tiered run) and degrade visibly.
    chaos_profile = {
        "seed": 7,
        "host_alloc_fail_p": 0.3,
        "copy_delay_p": 0.3,
        "copy_delay_s": 0.02,
    }
    chaos_engine = InferenceEngine(
        tier_cfg(True, chaos=json.dumps(chaos_profile)), metrics=cp.metrics
    )
    await chaos_engine.start()
    chaos_outs: list = []
    cp0 = prom()
    await drive(chaos_engine, prompts, sink=chaos_outs)
    await drive(chaos_engine, prompts)
    cp1 = prom()
    c_matched = cp1.get("mcpx_kv_prefix_matched_tokens_total", 0.0) - cp0.get(
        "mcpx_kv_prefix_matched_tokens_total", 0.0
    )
    c_prefilled = cp1.get("mcpx_engine_prefill_tokens_total", 0.0) - cp0.get(
        "mcpx_engine_prefill_tokens_total", 0.0
    )
    cst = chaos_engine.prefix_cache_stats()["tier"]
    chaos_ok = chaos_outs == single_outs
    await chaos_engine.aclose()
    if not chaos_ok:
        raise BenchGateError(
            "spill-tier chaos broke greedy output parity — faulted copies "
            "must degrade to destructive eviction, never serve bad KV"
        )

    # The single-tier baseline can collapse to EXACTLY zero hits at big
    # working-set ratios (every run destroyed before its repeat) — floor
    # the denominator at 1% so the ratio stays a finite, trackable number
    # instead of a null that reads as "phase didn't run".
    hit_ratio = round(
        tiered["token_hit_rate"] / max(single["token_hit_rate"], 0.01), 2
    )
    return {
        "requests": n_prompts * rounds,
        "rounds": rounds,
        "working_set_tokens": working_set,
        "resident_cap_tokens": cap_getter(),
        "working_set_ratio": round(working_set / max(1, cap_getter()), 2),
        "single": single,
        "tiered": tiered,
        "tier_token_hit_rate": tiered["token_hit_rate"],
        "tier_hit_ratio": hit_ratio,
        "spills": tiered["spills"],
        "readmits": tiered["readmits"],
        "destructive_evictions": tiered["destructive_evictions"],
        "tenants": {
            "victim": {"token_hit_rate": round(victim_thr, 4)},
            "thrash": {"token_hit_rate": round(thrash_thr, 4)},
        },
        "victim_token_hit_rate": round(victim_thr, 4),
        "tenant_hit_rate_spread": round(victim_thr - thrash_thr, 4),
        "warm_restart": {
            "cold_first_plan_prefill_tokens": cold_first,
            "warm_first_plan_prefill_tokens": warm_first,
            "prefill_ratio": (
                round(cold_first / warm_first, 2) if warm_first > 0 else None
            ),
            # First-plan wall (ms): both engines pay their first-dispatch
            # compiles (warmup off), so the comparison is symmetric; the
            # prefill-token fields above are the mechanism-direct view.
            "cold_first_plan_ms": single.get("first_plan_ms"),
            "warm_first_plan_ms": round(warm_first_ms, 1),
        },
        "warm_restart_prefill_ratio": (
            round(cold_first / warm_first, 2) if warm_first > 0 else None
        ),
        "chaos": {
            "profile": chaos_profile,
            "token_hit_rate": round(
                c_matched / max(1.0, c_matched + c_prefilled), 4
            ),
            "destructive_evictions": cst["destructive_evictions"],
            "denied_readmits": cst["denied_readmits"],
            "chaos_alloc_failures": cst["chaos_alloc_failures"],
            "parity_ok": chaos_ok,
        },
    }


# Span names -> attribution phase keys (tracing spine, mcpx/telemetry/
# tracing.py). Per request: scheduler queue wait, engine admit-wait
# (enqueue -> admission prefill start), cohort prefill, slab-resident
# decode, and downstream tool/microservice attempts (/plan has none; the
# key exists so /plan_and_execute workloads report it too).
_ATTR_PHASES = {
    "sched_queue": ("sched.acquire",),
    "engine_queue": ("engine.queue_wait",),
    "prefill": ("engine.prefill",),
    "decode": ("engine.decode",),
    "tools": ("attempt",),
}


async def _flight_phase(cp) -> "dict | None":
    """Flight recorder & worker-profiler overhead scenario (ISSUE 13
    acceptance): the SAME direct-plan workload served with the recorder +
    decode-loop profiler fully OFF (the default pass-through) and ON (a
    live-attached WorkerProfiler on the engine worker plus a FlightRecorder
    sampling at 4 Hz — harsher than the 1 Hz default), in interleaved
    best-of rounds so co-tenant CPU bursts can't poison one mode's only
    window. Reports ``flight_overhead_frac`` (1 - on/off plans-per-sec,
    the <3% acceptance number) and the ``worker_profile`` block — the
    worker thread's wall time attributed to named phases, with the >=95%
    attribution fraction the acceptance gates on. Skip with
    MCPX_BENCH_FLIGHT=0."""
    if os.environ.get("MCPX_BENCH_FLIGHT", "1") == "0":
        return None
    engine = getattr(cp.planner, "engine", None)
    if engine is None or engine.state != "ready":
        return None
    import random as _random
    import shutil
    import tempfile

    from mcpx.telemetry.flight import WorkerProfiler, build_flight_recorder
    from mcpx.utils.synth import intent_for

    records = await cp.registry.list_services()
    rng = _random.Random(31)
    n = int(os.environ.get("MCPX_BENCH_FLIGHT_REQUESTS", "96"))
    # Best-of-3 interleaved rounds per mode: each round is seconds on the
    # CPU proxy, so a single co-tenant burst in one mode's only window
    # would otherwise manufacture (or hide) the whole overhead budget.
    rounds = 3
    concurrency = min(engine.config.engine.max_batch_size, 16)
    base_pool = [f"{intent_for(records, rng)} [flt{i}]" for i in range(8)]

    async def _idle() -> None:
        while engine._slab.n_active or engine._queue.qsize():
            await asyncio.sleep(0.05)
        await asyncio.sleep(0.1)

    tag = {"n": 0}

    async def one_round() -> float:
        # Fresh cache-busted intents per round: every round pays the same
        # plan/prefill/decode work whatever ran before it.
        tag["n"] += 1
        intents = [
            f"{base_pool[i % len(base_pool)]} r{tag['n']}-{i}" for i in range(n)
        ]
        await _idle()
        sem = asyncio.Semaphore(concurrency)

        async def one(intent: str) -> None:
            async with sem:
                await cp.plan(intent, use_cache=False)

        t0 = time.monotonic()
        await asyncio.gather(*(one(i) for i in intents))
        await _idle()
        return n / max(1e-9, time.monotonic() - t0)

    fcfg = cp.config.telemetry.flight
    prev = (fcfg.enabled, fcfg.interval_s, fcfg.bundle_dir)
    # An operator-enabled startup profiler (profile_worker=true) must
    # survive this phase's attach/detach dance.
    prev_prof = engine._profiler
    off_rates: list[float] = []
    on_rates: list[float] = []
    worker_profile = None
    flight_status = None
    tmpdir = tempfile.mkdtemp(prefix="mcpx-flight-bench-")
    try:
        for _ in range(rounds):
            # OFF: the default pass-through (no profiler, no recorder).
            engine._profiler = None
            off_rates.append(await one_round())
            # ON: live-attached profiler + a 4 Hz recorder task.
            engine._profiler = WorkerProfiler()
            fcfg.enabled, fcfg.interval_s, fcfg.bundle_dir = (
                True, 0.25, tmpdir,
            )
            recorder = build_flight_recorder(cp)
            task = asyncio.create_task(recorder.run())
            try:
                on_rates.append(await one_round())
            finally:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
            # Profile snapshot while the profiler is still attached.
            worker_profile = engine.queue_stats()["worker_profile"]
            flight_status = recorder.status()
    finally:
        engine._profiler = prev_prof
        fcfg.enabled, fcfg.interval_s, fcfg.bundle_dir = prev
        shutil.rmtree(tmpdir, ignore_errors=True)
    best_off, best_on = max(off_rates), max(on_rates)
    return {
        "requests": n,
        "rounds": rounds,
        "plans_per_sec_off": round(best_off, 2),
        "plans_per_sec_on": round(best_on, 2),
        # The acceptance number: fractional headline cost of serving with
        # the recorder + profiler armed (negative = measurement noise).
        "flight_overhead_frac": round(1.0 - best_on / max(1e-9, best_off), 4),
        "worker_profile": worker_profile,
        "flight_samples": flight_status["samples"] if flight_status else 0,
        "flight_ring_len": flight_status["ring_len"] if flight_status else 0,
        "detectors": (
            sorted(flight_status["detectors"]) if flight_status else []
        ),
    }


async def _ledger_phase(cp) -> "dict | None":
    """Cost-ledger & usage-attribution scenario (ISSUE 14 acceptance): the
    SAME direct-plan workload served with the ledger fully OFF (the
    default pass-through) and ON (engine per-row accumulators + per-tenant
    usage fold + SLO observe), in interleaved best-of rounds like the
    flight phase. Reports ``ledger_overhead_frac`` (1 - on/off
    plans-per-sec, the <3% acceptance number) and the ``attribution``
    block: per-tenant itemized usage, the mean wall-attribution fraction,
    and the FLOP-conservation cross-check (sum of bills vs the engine's
    apportioned totals). Skip with MCPX_BENCH_LEDGER=0."""
    if os.environ.get("MCPX_BENCH_LEDGER", "1") == "0":
        return None
    engine = getattr(cp.planner, "engine", None)
    if engine is None or engine.state != "ready":
        return None
    import math as _math
    import random as _random

    from mcpx.telemetry import ledger as ledger_mod
    from mcpx.telemetry.ledger import RequestBill, UsageLedger
    from mcpx.telemetry.slo import SLOTracker
    from mcpx.utils.synth import intent_for

    records = await cp.registry.list_services()
    rng = _random.Random(47)
    n = int(os.environ.get("MCPX_BENCH_LEDGER_REQUESTS", "96"))
    rounds = 3
    tenants = ("acme", "globex", "initech", "default")
    concurrency = min(engine.config.engine.max_batch_size, 16)
    base_pool = [f"{intent_for(records, rng)} [led{i}]" for i in range(8)]

    async def _idle() -> None:
        while engine._slab.n_active or engine._queue.qsize():
            await asyncio.sleep(0.05)
        await asyncio.sleep(0.1)

    lcfg = cp.config.telemetry.ledger
    scfg = cp.config.slo
    usage: "UsageLedger | None" = None
    slo: "SLOTracker | None" = None
    tag = {"n": 0}

    async def one_round(billed: bool) -> float:
        tag["n"] += 1
        intents = [
            f"{base_pool[i % len(base_pool)]} r{tag['n']}-{i}" for i in range(n)
        ]
        await _idle()
        sem = asyncio.Semaphore(concurrency)

        async def one(k: int, intent: str) -> None:
            async with sem:
                tenant = tenants[k % len(tenants)]
                if not billed:
                    # Same tenant rotation as the ON arm: the cache
                    # governor's per-tenant accounting must be identical
                    # across modes, or the overhead delta would include
                    # tenant-governance work instead of just the ledger.
                    await cp.plan(intent, use_cache=False, tenant=tenant)
                    return
                # The middleware's bill lifecycle, inlined (this phase
                # drives cp.plan directly, the flight phase's style):
                # activate -> plan (engine items fold via the contextvar)
                # -> finalize -> usage/SLO observe.
                t0 = time.monotonic()
                bill = RequestBill(tenant=tenant, endpoint="/plan", t0=t0)
                token = ledger_mod.activate(bill)
                try:
                    eng0 = bill.engine_wall_ms()
                    _, latency_ms = await cp.plan(
                        intent, use_cache=False, tenant=tenant
                    )
                    bill.note_plan(latency_ms, bill.engine_wall_ms() - eng0)
                finally:
                    ledger_mod.deactivate(token)
                    total_ms = (time.monotonic() - t0) * 1e3
                    bill.finalize(status="ok", total_ms=total_ms)
                    usage.observe(bill)
                    slo.observe(
                        tenant=tenant, endpoint="/plan",
                        latency_ms=total_ms, error=False, degraded=False,
                    )

        t0 = time.monotonic()
        await asyncio.gather(*(one(k, i) for k, i in enumerate(intents)))
        await _idle()
        return n / max(1e-9, time.monotonic() - t0)

    prev = (lcfg.enabled, cp.ledger, cp.slo)
    off_rates: list[float] = []
    on_rates: list[float] = []
    totals0 = engine.ledger_totals()
    try:
        for _ in range(rounds):
            # OFF: the default pass-through (no bill anywhere).
            lcfg.enabled = False
            cp.ledger = cp.slo = None
            off_rates.append(await one_round(False))
            # ON: live-attached ledger + SLO tracker (fresh on the first
            # ON round so the attribution block is this phase's alone).
            if usage is None:
                usage = UsageLedger(lcfg, metrics=cp.metrics)
                slo = SLOTracker(scfg)
            lcfg.enabled = True
            cp.ledger, cp.slo = usage, slo
            on_rates.append(await one_round(True))
    finally:
        lcfg.enabled, cp.ledger, cp.slo = prev
    best_off, best_on = max(off_rates), max(on_rates)
    snap = usage.snapshot()
    bills = snap["recent"]
    attributed = [b["attributed_frac"] for b in bills if b["total_ms"] > 0]
    # FLOP conservation cross-check (the acceptance contract): the ledger
    # aggregate (every bill folded, unbounded — the recent ring drops old
    # bills past its cap) equals what the engine apportioned during the
    # ON rounds (same lazy-cost availability, same rounding contract).
    totals1 = engine.ledger_totals()
    bill_flops = snap["totals"]["flops"]
    engine_flops = totals1["flops"] - totals0["flops"]
    attribution = {
        "requests": snap["requests"],
        "wall_attributed_frac": (
            round(sum(attributed) / len(attributed), 4) if attributed else None
        ),
        "flops_per_plan": (
            round(snap["totals"]["flops"] / snap["requests"], 1)
            if snap["requests"]
            else None
        ),
        "decode_tokens_per_plan": (
            round(snap["totals"]["decode_tokens"] / snap["requests"], 2)
            if snap["requests"]
            else None
        ),
        "flops_conserved": bool(
            _math.isclose(bill_flops, engine_flops, rel_tol=1e-6, abs_tol=1.0)
        ),
        "tenants": {
            t: {
                "requests": acct["requests"],
                "decode_tokens": acct["decode_tokens"],
                "prefill_tokens": acct["prefill_tokens"],
                "flops": acct["flops"],
                "decode_ms": acct["decode_ms"],
            }
            for t, acct in snap["tenants"].items()
        },
    }
    return {
        "requests": n,
        "rounds": rounds,
        "plans_per_sec_off": round(best_off, 2),
        "plans_per_sec_on": round(best_on, 2),
        # The acceptance number: fractional headline cost of serving with
        # the ledger + SLO observe armed (negative = measurement noise).
        "ledger_overhead_frac": round(1.0 - best_on / max(1e-9, best_off), 4),
        "attribution": attribution,
        "slo": {
            "objectives": [
                {
                    "name": o["name"],
                    "budget_remaining": o["budget_remaining"],
                    "fast_burn": o["fast_burn"],
                }
                for o in slo.status()["global"]["objectives"]
            ],
        },
    }


def _attribution_from_traces(recs) -> "dict | None":
    """p50/p99 per-phase latency attribution over sampled trace records:
    where a request's wall time went, so a BENCH_*.json regression explains
    itself instead of just reporting a bigger p50 (ISSUE 4 satellite)."""
    rows = []
    for rec in recs:
        if rec.error:
            continue  # error traces attribute failure, not steady-state latency
        phases = {k: 0.0 for k in _ATTR_PHASES}
        for s in rec.spans:
            for key, names in _ATTR_PHASES.items():
                if s.name in names:
                    phases[key] += s.duration_ms
        phases["total"] = rec.total_ms
        rows.append(phases)
    if not rows:
        return None

    def q(vals: list, p: float) -> float:
        vs = sorted(vals)
        return vs[min(len(vs) - 1, int(p * (len(vs) - 1)))]

    keys = [*_ATTR_PHASES, "total"]
    p50 = {k: round(q([r[k] for r in rows], 0.5), 2) for k in keys}
    p99 = {k: round(q([r[k] for r in rows], 0.99), 2) for k in keys}
    tot = max(1e-9, p50["total"])
    return {
        "traces": len(rows),
        "p50_ms": p50,
        "p99_ms": p99,
        # Share of the p50 request: the number to read when a regression
        # lands — which phase grew. Shares need not sum to 1 (phases
        # overlap the un-instrumented remainder: HTTP parse, validation,
        # prompt build, host dispatch).
        "share_p50": {k: round(p50[k] / tot, 4) for k in _ATTR_PHASES},
    }


async def _attribution_phase(cp, base: str, records, rng, rate: float) -> "dict | None":
    """Latency-attribution sample (tracing spine): a short open-loop round
    at the phase-2 offered rate with a Tracer attached to the LIVE control
    plane (cp.tracer is read per request by the middleware), detached in a
    finally. Its own phase, after every headline scrape, so the headline
    p50 stays tracing-free and comparable to earlier rounds. Skip with
    MCPX_BENCH_TRACE=0."""
    if os.environ.get("MCPX_BENCH_TRACE", "1") == "0":
        return None
    from aiohttp import ClientSession, TCPConnector

    from mcpx.telemetry.tracing import Tracer
    from mcpx.utils.synth import intent_for

    n = int(os.environ.get("MCPX_BENCH_TRACE_REQUESTS", "96"))
    rate = max(0.5, rate)
    prev = cp.tracer
    cp.tracer = Tracer(enabled=True, sample_rate=1.0, ring_size=max(1024, n))
    try:
        async with ClientSession(connector=TCPConnector(limit=0)) as session:

            async def one(intent: str, delay: float) -> None:
                await asyncio.sleep(delay)
                try:
                    async with session.post(
                        f"{base}/plan", json={"intent": intent}
                    ) as resp:
                        await resp.json()
                except Exception:  # noqa: BLE001 - a failed request simply contributes no trace
                    pass

            intents = [f"{intent_for(records, rng)} [attr{i}]" for i in range(n)]
            await asyncio.gather(*(one(x, i / rate) for i, x in enumerate(intents)))
        recs = cp.tracer.traces()
    finally:
        cp.tracer = prev
    return _attribution_from_traces(recs)


async def _chaos_phase(cp, base: str) -> "dict | None":
    """Fault-domain resilience scenario (ISSUE 5 acceptance): wrap the live
    orchestrator's transport in a seeded ChaosTransport (flapping primaries,
    injected errors/timeouts, healthy-ish fallbacks) and serve the SAME
    /execute workload twice — resilience OFF (pre-resilience executor:
    plain retries + fallbacks) then ON (circuit breakers + deadline budget
    + hedging) — under the same fault profile and seed. A request SUCCEEDS
    when it returns status "ok" within its deadline; an arrival after the
    deadline is an SLO miss whatever the body says. Engine-free (/execute
    only), runs dead last, restores the transport in a finally. Skip with
    MCPX_BENCH_CHAOS=0."""
    if os.environ.get("MCPX_BENCH_CHAOS", "1") == "0":
        return None
    from aiohttp import ClientSession, TCPConnector

    from mcpx.core.config import ResilienceConfig
    from mcpx.resilience import Resilience
    from mcpx.resilience.chaos import ChaosProfile, ChaosTransport

    n = int(os.environ.get("MCPX_BENCH_CHAOS_REQUESTS", "160"))
    deadline_ms = float(os.environ.get("MCPX_BENCH_CHAOS_DEADLINE_MS", "400"))
    orch = cp.orchestrator
    prev_transport = orch._transport
    prev_resilience = orch._resilience
    local = getattr(prev_transport, "local", None)
    if local is None:
        return None  # non-router transport: nowhere to host the fake services

    async def healthy(payload):
        return {"ok": True}

    for name in ("chaos-a", "chaos-a-fb", "chaos-b", "chaos-b-fb"):
        local.register(name, healthy)
    # Primaries are badly degraded (one flapping hard-down on a cycle, both
    # erroring/timing out), fallbacks nearly healthy — the fault geometry
    # where breakers (stop dialing the dead primary), budget (stop burning
    # the deadline on its timeouts) and hedging (duplicate the laggard)
    # each earn their keep.
    profile = ChaosProfile.from_dict(
        {
            "seed": 1234,
            "endpoints": {
                "local://chaos-a": {
                    "error_rate": 0.2,
                    "timeout_rate": 0.55,
                    "latency_ms": 5,
                    "flap_period_s": 4.0,
                    "flap_down_s": 2.0,
                },
                "local://chaos-b": {
                    "error_rate": 0.2,
                    "timeout_rate": 0.5,
                    "latency_ms": 5,
                },
                "local://chaos-*-fb": {"error_rate": 0.05, "latency_ms": 10},
            },
        }
    )
    graph = {
        "nodes": [
            {
                "name": "a", "service": "chaos-a", "endpoint": "local://chaos-a",
                "retries": 2, "timeout_s": 0.15,
                "fallbacks": ["local://chaos-a-fb"],
            },
            {
                "name": "b", "service": "chaos-b", "endpoint": "local://chaos-b",
                "retries": 2, "timeout_s": 0.15,
                "fallbacks": ["local://chaos-b-fb"], "inputs": {"x": "a"},
            },
        ],
        "edges": [{"src": "a", "dst": "b"}],
    }

    async def run_round(resilient: bool) -> dict:
        # Fresh ChaosTransport per round: same profile, same seed, flap
        # phase restarted — both modes face the same fault stream.
        orch._transport = ChaosTransport(prev_transport, profile)
        orch._resilience = (
            Resilience(
                ResilienceConfig(enabled=True),
                telemetry=cp.telemetry,
                metrics=cp.metrics,
            )
            if resilient
            else None
        )
        counts = {"ok_within": 0, "ok_late": 0, "failed": 0, "error": 0,
                  "overrun": 0}
        lat: list[float] = []
        async with ClientSession(connector=TCPConnector(limit=0)) as session:
            sem = asyncio.Semaphore(16)

            async def one(i: int) -> None:
                async with sem:
                    t0 = time.monotonic()
                    try:
                        async with session.post(
                            f"{base}/execute",
                            json={"graph": graph, "payload": {}},
                            headers={"X-MCPX-Deadline-Ms": str(deadline_ms)},
                        ) as resp:
                            body = await resp.json()
                            status = body.get("status")
                    except Exception:  # noqa: BLE001 - counted, not fatal
                        counts["error"] += 1
                        return
                    ms = (time.monotonic() - t0) * 1e3
                    lat.append(ms)
                    if ms > deadline_ms:
                        counts["overrun"] += 1
                    if status == "ok":
                        counts["ok_within" if ms <= deadline_ms else "ok_late"] += 1
                    else:
                        counts["failed"] += 1

            await asyncio.gather(*(one(i) for i in range(n)))
        lat.sort()
        return {
            "success_rate": round(counts["ok_within"] / max(1, n), 4),
            "overrun_share": round(counts["overrun"] / max(1, n), 4),
            "ok_share": round(
                (counts["ok_within"] + counts["ok_late"]) / max(1, n), 4
            ),
            "p99_ms": round(lat[int(0.99 * (len(lat) - 1))], 1) if lat else None,
            **counts,
        }

    try:
        # Baseline (resilience OFF) first: its completions also warm the
        # TelemetryStore EWMAs the ON round's hedge delays derive from.
        baseline = await run_round(False)
        resilient = await run_round(True)
    finally:
        orch._transport = prev_transport
        orch._resilience = prev_resilience
    return {
        "requests": n,
        "deadline_ms": deadline_ms,
        "seed": profile.seed,
        "resilient": resilient,
        "baseline": baseline,
        # The three acceptance numbers, spelled the way the driver greps.
        "chaos_success_rate": resilient["success_rate"],
        "chaos_success_rate_baseline": baseline["success_rate"],
        "deadline_overrun_share": resilient["overrun_share"],
        "deadline_overrun_share_baseline": baseline["overrun_share"],
    }


async def _kernel_phase(cp) -> "dict | None":
    """Ragged-kernel & fused-dispatch scenario (ISSUE 15 acceptance): the
    SAME greedy mixed stream served on a DEDICATED 1×1 engine (spec-phase
    rationale: per-chip decode economics, no virtual-mesh artifact) in two
    dispatch cadences at the same offered load —

      - **per_step**: a TRUE one-forward-per-dispatch baseline
        (``decode_steps_per_tick=1`` AND ``steps_per_dispatch=1`` — the
        tick is itself a fused window, so leaving it at 4 would measure
        fused-vs-more-fused), host bookkeeping every forward — the
        cadence whose dispatch overhead the r07 profiler billed at ~80%
        of worker wall;
      - **fused**: the configured fused window — one dispatch covers
        ``decode_steps_per_tick × steps_per_dispatch`` forwards, per-row
        done masks as data.

    Reports per-arm ``decode_dispatches_per_token`` (segments/tokens
    counter deltas — the ≥4× acceptance drop) and ``fused_decode_speedup``
    (fused/per-step tokens-per-sec, interleaved best-of rounds — the
    "tokens/s no worse than per-step" guard). Two honesty gates raise
    ``BenchGateError``: greedy outputs must be byte-identical across the
    two cadences (mid-window retirement must not change what rows emit),
    and across the RAGGED KERNEL vs the pure-jnp reference — a second
    dedicated engine serves the same prompts with ``use_pallas=false``
    and every token must match (the interpret-parity gate: tier-1's CPU
    proxy runs the same kernel body TPUs run). The kernel engine's
    per-path ``pallas_paths`` block rides along so the phase's own route
    is auditable. Skip with MCPX_BENCH_KERNEL=0."""
    if os.environ.get("MCPX_BENCH_KERNEL", "1") == "0":
        return None
    serving = getattr(cp.planner, "engine", None)
    if serving is None or serving.state != "ready":
        return None
    from mcpx.core.config import MCPXConfig
    from mcpx.engine.engine import InferenceEngine

    n = max(4, int(os.environ.get("MCPX_BENCH_KERNEL_REQUESTS", "48")))
    base_dict = serving.config.to_dict()
    base_dict["engine"]["data_axis"] = 1
    base_dict["engine"]["model_axis"] = 1
    # Hetero slab, speculation OFF: the fused window multiplies the
    # while-loop segments only (the spec segment's unrolled iterations are
    # deliberately excluded — see EngineConfig.steps_per_dispatch), so a
    # spec engine would measure nothing here; the spec phase (7) already
    # exercises the kernel's verify path.
    base_dict["engine"]["hetero_batch"] = True
    base_dict["engine"]["speculative"] = {"enabled": False}
    base_dict["engine"]["warmup_compile"] = False
    base_dict["engine"]["admit_min_free"] = 1
    base_dict["engine"]["admit_max_wait_s"] = 0.0

    def mk_engine(use_pallas: bool) -> InferenceEngine:
        d = json.loads(json.dumps(base_dict))
        d["engine"]["use_pallas"] = use_pallas
        return InferenceEngine(MCPXConfig.from_dict(d), metrics=cp.metrics)

    engine = mk_engine(_pallas_on())
    await engine.start()
    tok = engine.tokenizer
    ecfg = engine.config.engine
    fused_k = max(2, ecfg.steps_per_dispatch)
    base_tick = max(1, ecfg.decode_steps_per_tick)
    budget = max(8, min(32, ecfg.max_decode_len))
    concurrency = min(2 * ecfg.max_batch_size, 64, max(1, n // 3))
    # A shared prompt head so the radix cache matches and the SUFFIX
    # prefill path (the seven-PR jnp fork this PR retires) actually runs
    # through the kernel during the phase, not just plain decode.
    head = "kernel phase shared header: compose the registry services."

    async def _idle(eng) -> None:
        while eng._slab.n_active or eng._queue.qsize():
            await asyncio.sleep(0.05)
        await asyncio.sleep(0.1)

    def prompt_for(i: int) -> list[int]:
        free = i % 3 == 2  # two constrained rows per free row
        return (
            tok.encode(f"{head} intent {i}: JSON:"),
            not free,
        )

    async def one(eng, i: int, sem: asyncio.Semaphore, sink: "dict | None") -> None:
        ids, constrained = prompt_for(i)
        async with sem:
            r = await eng.generate(
                ids, max_new_tokens=budget, constrained=constrained,
                temperature=0.0,
            )
        if sink is not None:
            sink[i] = r.token_ids

    async def set_cadence(eng, per_step: bool) -> None:
        # per_step = a TRUE one-forward-per-dispatch baseline: both fusion
        # levers at 1 (decode_steps_per_tick is itself a fused window —
        # leaving it at 4 would measure fused-vs-more-fused). The fused
        # arm restores the configured cadence. iters is a jit static, so
        # each cadence is its own (warmed) executable; the flip lands at
        # the next dispatch — flipped only on an idle slab.
        await _idle(eng)
        eng.config.engine.decode_steps_per_tick = 1 if per_step else base_tick
        eng.config.engine.steps_per_dispatch = 1 if per_step else fused_k

    ROUNDS = 3
    chunk_n = max(1, n // ROUNDS)
    concurrency = min(concurrency, chunk_n)
    acc = {
        m: {"tok": 0.0, "seg": 0.0, "elapsed": 0.0, "rounds": []}
        for m in ("per_step", "fused")
    }
    sinks: dict = {"per_step": {}, "fused": {}}
    warmed: set = set()
    try:
        for r in range(ROUNDS):
            lo, hi = r * n // ROUNDS, (r + 1) * n // ROUNDS
            if lo >= hi:
                continue
            for mode in ("per_step", "fused"):
                await set_cadence(engine, mode == "per_step")
                if mode not in warmed:
                    # Untimed warm pass: compile this cadence's segment
                    # executable (iters is a static) + prefill buckets
                    # outside the timed region; disjoint ids so no timed
                    # request inherits warm-request KV.
                    warm_sem = asyncio.Semaphore(concurrency)
                    await asyncio.gather(
                        *(
                            one(engine, 1_000_000 + i, warm_sem, None)
                            for i in range(min(chunk_n, concurrency))
                        )
                    )
                    await _idle(engine)
                    warmed.add(mode)
                prom0 = _parse_prom(cp.metrics.render().decode())
                sem = asyncio.Semaphore(concurrency)
                t0 = time.monotonic()
                await asyncio.gather(
                    *(one(engine, i, sem, sinks[mode]) for i in range(lo, hi))
                )
                elapsed = time.monotonic() - t0
                prom1 = _parse_prom(cp.metrics.render().decode())
                a = acc[mode]
                r_tok = prom1.get(
                    "mcpx_engine_decode_tokens_total", 0.0
                ) - prom0.get("mcpx_engine_decode_tokens_total", 0.0)
                a["tok"] += r_tok
                a["seg"] += prom1.get(
                    "mcpx_engine_segments_total", 0.0
                ) - prom0.get("mcpx_engine_segments_total", 0.0)
                a["elapsed"] += elapsed
                a["rounds"].append(
                    {
                        "decode_tok_s": round(r_tok / max(1e-9, elapsed), 1),
                        "plans_per_sec": round(
                            (hi - lo) / max(1e-9, elapsed), 2
                        ),
                    }
                )
        kernel_paths = engine.pallas_paths()
    finally:
        await engine.aclose()

    # Cadence parity gate: the SAME greedy request byte-identical across
    # per-step and fused dispatch (mid-window retirement, admission
    # cadence and done-row idling must never change what a row emits).
    broken = [i for i in sinks["per_step"] if sinks["fused"].get(i) != sinks["per_step"][i]]
    if broken:
        raise BenchGateError(
            f"fused dispatch changed greedy outputs on {len(broken)}/"
            f"{len(sinks['per_step'])} requests (fused vs per-step)"
        )

    # Interpret-parity gate: the ragged kernel's tokens vs the pure-jnp
    # reference path, end to end through a second dedicated engine. Only
    # meaningful when the kernel arm actually resolved the kernel route —
    # under MCPX_BENCH_PALLAS=0 (or a fused-jnp-only smoke artifact) both
    # engines would serve jnp and the gate would vacuously "pass" while
    # reading as kernel validation; report None instead and skip the
    # reference engine's whole serve.
    interpret_parity: "bool | None" = None
    if kernel_paths["enabled"]:
        ref_sink: dict = {}
        ref_engine = mk_engine(False)
        await ref_engine.start()
        try:
            sem = asyncio.Semaphore(concurrency)
            await asyncio.gather(
                *(one(ref_engine, i, sem, ref_sink) for i in range(n))
            )
            await _idle(ref_engine)
        finally:
            await ref_engine.aclose()
        diverged = [
            i for i in sinks["fused"] if ref_sink.get(i) != sinks["fused"][i]
        ]
        if diverged:
            raise BenchGateError(
                f"ragged kernel diverged from the jnp reference on "
                f"{len(diverged)}/{len(sinks['fused'])} greedy requests "
                "(interpret-parity gate)"
            )
        interpret_parity = True

    def mode_res(mode: str) -> dict:
        a = acc[mode]
        return {
            "decode_tok_s": max(r["decode_tok_s"] for r in a["rounds"]),
            "plans_per_sec": max(r["plans_per_sec"] for r in a["rounds"]),
            "decode_tokens": int(a["tok"]),
            "segments": int(a["seg"]),
            # Cadence is deterministic — totals across rounds, not best-of.
            "dispatches_per_token": round(a["seg"] / max(1.0, a["tok"]), 4),
            "rounds": a["rounds"],
        }

    per_step, fused = mode_res("per_step"), mode_res("fused")
    return {
        "requests": n,
        "rounds": ROUNDS,
        "steps_per_dispatch": fused_k,
        "fused_window_forwards": base_tick * fused_k,
        "per_step": per_step,
        "fused": fused,
        # The two acceptance numbers, spelled the way the driver greps:
        # dispatch cadence under the fused window (vs the per-step arm
        # right next to it) and the wall-clock guard.
        "decode_dispatches_per_token": fused["dispatches_per_token"],
        "decode_dispatches_per_token_per_step": per_step["dispatches_per_token"],
        "dispatch_reduction": round(
            per_step["dispatches_per_token"]
            / max(1e-9, fused["dispatches_per_token"]),
            2,
        ),
        "fused_decode_speedup": round(
            fused["decode_tok_s"] / max(1e-9, per_step["decode_tok_s"]), 3
        ),
        # True = gated above (divergence raised); None = kernel arm not
        # kernel-routed (operator forced jnp), so there was nothing to
        # validate and no reference engine ran.
        "interpret_parity": interpret_parity,
        "cadence_parity": True,  # gated above: divergence raised
        "pallas_paths": kernel_paths,
    }


class _SimReplicaEngine:
    """Deterministic engine stand-in for the ROUTER-LEVEL cluster arms.

    On the CPU proxy every real engine replica shares the same host cores,
    so compute-bound plans/s cannot scale with replica count no matter what
    the router does — the scaling/failover/affinity arms would measure host
    contention, not routing. This stand-in gives each replica its own
    bounded service capacity (``slots`` concurrent requests, a fixed
    ``service_s`` wall per request via asyncio.sleep — wall time the event
    loop concurrency genuinely overlaps) and a radix-style prefix cache at
    FAMILY granularity (LRU over page-aligned prompt heads, capacity
    ``cache_families``), so plans/s, p99-under-kill and routed-vs-RR token
    hit rate are measured through the REAL EnginePool/RoutingPipeline with
    replica economics a single host can honestly host. The phase labels
    these numbers basis="router-sim"; the warm-rejoin arm uses real engines
    and inherits the run's measurement basis.
    """

    def __init__(
        self, *, slots: int, service_s: float, prefix_tokens: int,
        cache_families: int,
    ) -> None:
        from collections import OrderedDict

        self.state = "cold"
        self.metrics = None
        self.costs = None
        self.tokenizer = None
        self._slots = slots
        self._service_s = service_s
        self._sem = asyncio.Semaphore(slots)
        self._prefix_tokens = prefix_tokens
        self._cache: "OrderedDict[tuple, None]" = OrderedDict()
        self._cache_cap = cache_families
        self.hit_tokens = 0
        self.miss_tokens = 0
        self._depth = 0
        self._active = 0

    async def start(self) -> None:
        self.state = "ready"

    async def aclose(self) -> None:
        self.state = "closed"

    async def generate(self, prompt_ids, **kw):
        from mcpx.core.errors import EngineError

        self._depth += 1
        async with self._sem:
            self._depth -= 1
            self._active += 1
            try:
                await asyncio.sleep(self._service_s)
            finally:
                self._active -= 1
        if self.state != "ready":
            # Killed mid-request: the pool re-steers this request to a
            # survivor (where it re-prefills — counted as that replica's
            # miss, exactly like a real cold re-prefill).
            raise EngineError("replica closed mid-request")
        head = tuple(prompt_ids[: self._prefix_tokens])
        if head in self._cache:
            self._cache.move_to_end(head)
            self.hit_tokens += len(head)
        else:
            self.miss_tokens += len(head)
            self._cache[head] = None
            while len(self._cache) > self._cache_cap:
                self._cache.popitem(last=False)
        return None

    def queue_stats(self) -> dict:
        seen = self.hit_tokens + self.miss_tokens
        return {
            "pallas": False,
            "depth": self._depth,
            "active": self._active,
            "service_ewma_s": self._service_s,
            "eta_s": self._service_s * (self._depth + self._active) / self._slots,
            "depth_constrained": 0,
            "depth_free": self._depth,
            "hol_wait_ms": 0.0,
            "resident_grammars": 0,
            "prefix_nodes": len(self._cache),
            "prefix_resident_pages": len(self._cache),
            "prefix_hit_rate": self.hit_tokens / max(1, seen),
            "prefix_token_hit_rate": self.hit_tokens / max(1, seen),
            "prefix_host_pages": 0,
            "prefix_spills": 0,
            "prefix_readmits": 0,
            "prefix_destructive_evictions": 0,
            "spec_accept_rate": 0.0,
            "spec_accept_rate_constrained": 0.0,
            "spec_accept_rate_free": 0.0,
        }


async def _cluster_phase(cp) -> "dict | None":
    """Cluster scale-out scenario (ISSUE 16 acceptance), four arms:

      1. **scaling** — closed-loop plans/s through the real EnginePool at
         1/2/4 replicas of fixed per-replica capacity (router-sim basis,
         see _SimReplicaEngine) — near-linear is the acceptance.
      2. **one-down** — open-loop at ~45% of 4-replica capacity; one
         replica is KILLED mid-phase. In-flight requests on the dead
         replica re-steer to survivors (one retry, re-prefill there), so
         client-visible failures must be ZERO and p99 must stay flat-ish
         (3 replicas still clear the offered load). The dead slot then
         rejoins with a bumped generation.
      3. **affinity A/B** — the SAME shuffled repeat-heavy stream (more
         prefix families than one replica's cache holds, fewer than the
         pool holds when split by rendezvous hash) routed by the default
         affinity pipeline vs RoundRobinPolicy; routed token hit rate
         must beat round-robin by a real margin (gated).
      4. **warm rejoin** — REAL engines (2-replica pool, tiny geometry,
         kv_tier + cluster.warm_snapshot_dir): serve a prompt on its
         affinity replica, kill it (the close writes the PR 11 KV
         snapshot), rejoin (the fresh engine restores it in start()),
         and assert the rejoined replica's first plan prefills strictly
         fewer tokens than cold — greedy output byte-identical.

    Dedicated pools only — the serving engine sits idle. Skip with
    MCPX_BENCH_CLUSTER=0."""
    if os.environ.get("MCPX_BENCH_CLUSTER", "1") == "0":
        return None
    serving = getattr(cp.planner, "engine", None)
    if serving is None or serving.state != "ready":
        return None
    import contextlib
    import random
    import shutil
    import tempfile

    from mcpx.cluster import EnginePool, RoundRobinPolicy, RoutingPipeline
    from mcpx.core.config import MCPXConfig

    SLOTS = 4
    SERVICE_S = 0.02
    PREFIX_TOKENS = 64
    FAMILIES = 33  # coprime with every replica count used below
    CACHE_CAP = 12  # < FAMILIES (RR thrashes), > FAMILIES/4 (affinity fits)
    ARMS = (1, 2, 4)

    def sim_pool(n: int, *, pipeline=None) -> EnginePool:
        cfg = MCPXConfig.from_dict(
            {
                "planner": {"kind": "llm"},
                "engine": {"kv_page_size": 16},
                "cluster": {
                    "enabled": True,
                    "replicas": n,
                    "affinity": True,
                    "affinity_prefix_tokens": PREFIX_TOKENS,
                    # Refresh faster than a service interval: the queue
                    # baseline routes off the scoreboard snapshot, and a
                    # snapshot stale by several completions re-piles onto
                    # the same replica between refreshes.
                    "scoreboard_interval_s": 0.01,
                },
            }
        )
        return EnginePool(
            cfg,
            engine_factory=lambda i, c: _SimReplicaEngine(
                slots=SLOTS,
                service_s=SERVICE_S,
                prefix_tokens=PREFIX_TOKENS,
                cache_families=CACHE_CAP,
            ),
            pipeline=pipeline,
        )

    def family_stream(n_requests: int, seed: int) -> list:
        """Repeat-heavy prompts: a per-family 64-token head (the affinity
        key) + a unique tail; shuffled so round-robin sprays families."""
        rng = random.Random(seed)
        prompts = [
            [1000 + (i % FAMILIES) * 131 + t for t in range(PREFIX_TOKENS)]
            + [rng.randrange(20000, 90000) for _ in range(8)]
            for i in range(n_requests)
        ]
        rng.shuffle(prompts)
        return prompts

    async def with_pool(pool, body):
        await pool.start()
        sb = asyncio.create_task(pool.run_scoreboard())
        try:
            return await body(pool)
        finally:
            sb.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await sb
            await pool.aclose()

    # ---- arm 1: closed-loop plans/s at 1/2/4 replicas.
    pps: dict[str, float] = {}
    for n in ARMS:
        n_req = 80 * n
        prompts = family_stream(n_req, seed=3)

        async def closed(pool, n_req=n_req, prompts=prompts, n=n):
            semc = asyncio.Semaphore(2 * SLOTS * n)

            async def one(p):
                async with semc:
                    await pool.generate(p, max_new_tokens=2)

            t0 = time.monotonic()
            await asyncio.gather(*(one(p) for p in prompts))
            return n_req / (time.monotonic() - t0)

        pps[str(n)] = round(await with_pool(sim_pool(n), closed), 1)
    linearity = round(pps[str(ARMS[-1])] / (ARMS[-1] * pps["1"]), 3)
    if linearity < 0.7:
        raise BenchGateError(
            f"cluster plans/s scaling_linearity={linearity} < 0.7 at "
            f"{ARMS[-1]} replicas — routing serializes what the replicas "
            "could overlap"
        )

    # ---- arm 2: open-loop p99 with one replica killed mid-phase.
    rate = 0.45 * 4 * SLOTS / SERVICE_S
    n_open = int(rate * 1.6)
    kill_at_s = 0.6

    async def open_arm(pool, *, kill: bool):
        prompts = family_stream(n_open, seed=5)
        lat: list[float] = []
        failures = 0

        async def one(i: int) -> None:
            nonlocal failures
            await asyncio.sleep(i / rate)
            t0 = time.monotonic()
            try:
                await pool.generate(prompts[i], max_new_tokens=2)
            except Exception:  # noqa: BLE001 - counted, gated below
                failures += 1
                return
            lat.append((time.monotonic() - t0) * 1e3)

        killer = None
        if kill:
            async def do_kill():
                await asyncio.sleep(kill_at_s)
                await pool.kill(1)

            killer = asyncio.create_task(do_kill())
        await asyncio.gather(*(one(i) for i in range(n_open)))
        if killer is not None:
            await killer
        rejoin_gen = None
        if kill:
            await pool.rejoin(1)
            rejoin_gen = pool.replicas[1].generation
        lat.sort()
        return {
            "p99_ms": round(lat[int(0.99 * (len(lat) - 1))], 1),
            "served": len(lat),
            "failures": failures,
            "resteered": pool.resteers,
            "rejoin_generation": rejoin_gen,
        }

    base_arm = await with_pool(
        sim_pool(4), lambda pool: open_arm(pool, kill=False)
    )
    down_arm = await with_pool(
        sim_pool(4), lambda pool: open_arm(pool, kill=True)
    )
    if down_arm["failures"] > 0:
        raise BenchGateError(
            f"replica kill leaked {down_arm['failures']} client-visible "
            "failures — the router must re-steer everything beyond the "
            "dead replica's resident rows"
        )
    p99_ratio = round(down_arm["p99_ms"] / max(1e-9, base_arm["p99_ms"]), 2)
    if p99_ratio > 3.0:
        raise BenchGateError(
            f"p99 with one replica down is {p99_ratio}x the all-up "
            "baseline — failover is not absorbing the lost capacity"
        )

    # ---- arm 3: routed (affinity) vs round-robin prefix token hit rate.
    async def hit_arm(pool) -> dict:
        prompts = family_stream(FAMILIES * 6, seed=7)
        semc = asyncio.Semaphore(12)

        async def one(p):
            async with semc:
                await pool.generate(p, max_new_tokens=2)

        await asyncio.gather(*(one(p) for p in prompts))
        hit = sum(r.engine.hit_tokens for r in pool.replicas)
        miss = sum(r.engine.miss_tokens for r in pool.replicas)
        return {
            "token_hit_rate": round(hit / max(1, hit + miss), 4),
            "requests": len(prompts),
            "affinity_hits": sum(r.affinity_hits for r in pool.replicas),
            "scoreboard": pool.scoreboard_snapshot(),
        }

    routed = await with_pool(sim_pool(4), hit_arm)
    rr = await with_pool(
        sim_pool(4, pipeline=RoutingPipeline([RoundRobinPolicy()])), hit_arm
    )
    margin = round(routed["token_hit_rate"] - rr["token_hit_rate"], 4)
    if margin <= 0.1:
        raise BenchGateError(
            f"routed token_hit_rate={routed['token_hit_rate']} vs "
            f"round_robin={rr['token_hit_rate']} (margin {margin} <= 0.1) "
            "— prefix affinity is not preserving KV locality"
        )

    # ---- arm 4: warm rejoin through REAL engines (PR 11 KV snapshot as
    # the replica warm-up path).
    snap_dir = tempfile.mkdtemp(prefix="mcpx-cluster-")
    d = serving.config.to_dict()
    d["engine"].update(
        {
            "data_axis": 1,
            "model_axis": 1,
            "warmup_compile": False,
            "hetero_batch": False,
            "max_batch_size": 4,
            "max_pages_per_seq": 16,
            "kv_page_size": 16,
            "max_decode_len": 8,
            "prefix_cache": True,
            "prefix_cache_entries": 4096,
        }
    )
    d["engine"]["speculative"] = {"enabled": False}
    d["engine"]["kv_tier"] = {"enabled": True, "host_mb": 64.0,
                              "copy_tokens_per_cycle": 4096}
    d["planner"]["kind"] = "llm"
    d["cluster"] = {
        "enabled": True,
        "replicas": 2,
        "affinity": True,
        "affinity_prefix_tokens": PREFIX_TOKENS,
        "warm_snapshot_dir": snap_dir,
    }
    prompt = serving.tokenizer.encode(
        "cluster warm rejoin probe: " + "compose rank fetch join " * 12
    )[:128]
    cold_aligned = float((len(prompt) // 16) * 16)

    def prom() -> dict:
        return _parse_prom(cp.metrics.render().decode())

    async def pool_idle(pool) -> None:
        for r in pool.replicas:
            eng = r.engine
            if getattr(eng, "state", "") != "ready":
                continue
            while eng._slab.n_active or eng._queue.qsize():
                await asyncio.sleep(0.02)
        await asyncio.sleep(0.05)

    rpool = EnginePool(MCPXConfig.from_dict(d), metrics=cp.metrics)
    try:
        await rpool.start()
        target = rpool._affinity_replica(prompt).index
        pf0 = prom().get("mcpx_engine_prefill_tokens_total", 0.0)
        r_cold = await rpool.generate(
            prompt, max_new_tokens=2, constrained=False, temperature=0.0
        )
        await pool_idle(rpool)
        cold_first = prom().get("mcpx_engine_prefill_tokens_total", 0.0) - pf0
        await rpool.kill(target)  # clean close writes the KV snapshot
        await rpool.rejoin(target)  # fresh engine restores it in start()
        pf1 = prom().get("mcpx_engine_prefill_tokens_total", 0.0)
        r_warm = await rpool.generate(
            prompt, max_new_tokens=2, constrained=False, temperature=0.0
        )
        await pool_idle(rpool)
        warm_first = prom().get("mcpx_engine_prefill_tokens_total", 0.0) - pf1
        rejoin_landed = rpool.replicas[target].routed >= 2
        if r_warm.token_ids != r_cold.token_ids:
            raise BenchGateError(
                "rejoined replica's greedy output diverged from cold — "
                "restored KV must attend byte-identically"
            )
        if not warm_first < cold_first:
            raise BenchGateError(
                f"rejoined replica prefilled {warm_first} tokens vs "
                f"{cold_first} cold — the warm-restart snapshot did not "
                "warm the replica"
            )
    finally:
        with contextlib.suppress(Exception):
            await rpool.aclose()
        shutil.rmtree(snap_dir, ignore_errors=True)

    warm_ratio = round(cold_first / warm_first, 2) if warm_first > 0 else None
    return {
        # Basis labels (ROADMAP item 4): arms 1-3 measure the real router
        # over simulated per-replica capacity; arm 4 is real engines on
        # the run's platform basis.
        "basis": {"scaling": "router-sim", "warm_rejoin": _measurement_basis()},
        "sim": {
            "slots": SLOTS,
            "service_s": SERVICE_S,
            "families": FAMILIES,
            "cache_families": CACHE_CAP,
        },
        "plans_per_sec": pps,
        "cluster_scaling_linearity": linearity,
        "one_down": {
            "rate_per_s": round(rate, 1),
            "requests": n_open,
            "kill_at_s": kill_at_s,
            "p99_ms_baseline": base_arm["p99_ms"],
            "p99_ms_one_down": down_arm["p99_ms"],
            "resteered": down_arm["resteered"],
            "failures": down_arm["failures"],
            "rejoin_generation": down_arm["rejoin_generation"],
        },
        "cluster_p99_one_down_ratio": p99_ratio,
        "affinity": {
            "requests": routed["requests"],
            "routed": {k: routed[k] for k in ("token_hit_rate", "affinity_hits")},
            "round_robin": {"token_hit_rate": rr["token_hit_rate"]},
        },
        "cluster_routed_token_hit_rate": routed["token_hit_rate"],
        "cluster_rr_token_hit_rate": rr["token_hit_rate"],
        "cluster_affinity_hit_margin": margin,
        "warm_rejoin": {
            "replica": target,
            "cold_first_plan_prefill_tokens": cold_first,
            "cold_first_plan_prefill_aligned": cold_aligned,
            "rejoin_first_plan_prefill_tokens": warm_first,
            "prefill_ratio": warm_ratio,
            "landed_on_rejoined": rejoin_landed,
            "parity_ok": True,
        },
        "cluster_warm_rejoin_prefill_ratio": warm_ratio,
        "scoreboard": routed["scoreboard"],
    }


async def _provenance_phase(cp) -> "dict | None":
    """Decision-provenance overhead scenario (ISSUE 19 acceptance): the
    SAME direct-plan workload served with the provenance recorder OFF
    (``recorder=None`` — the default pass-through) and ON (a live
    ProvenanceRecorder whose trail the workload begins/ends per request,
    exactly what the server middleware does), in interleaved best-of
    rounds like the flight/ledger phases. BOTH arms open a root span per
    request at sample rate 1.0, so ``provenance_overhead_frac`` isolates
    the recorder's own cost — trail contextvar, decision child spans,
    counters — not tracing's, which has its own phase gate (<3%
    acceptance). Also reports ``explanation_coverage``: the fraction of
    ON-arm traces whose /explain output validates AND names the plan
    decision this workload is guaranteed to make. Skip with
    MCPX_BENCH_PROVENANCE=0."""
    if os.environ.get("MCPX_BENCH_PROVENANCE", "1") == "0":
        return None
    engine = getattr(cp.planner, "engine", None)
    if engine is None or engine.state != "ready":
        return None
    import random as _random

    from mcpx.telemetry import provenance as prov_mod
    from mcpx.telemetry import tracing
    from mcpx.telemetry.provenance import (
        ProvenanceRecorder,
        build_explanation,
        validate_explanation,
    )
    from mcpx.telemetry.tracing import Tracer
    from mcpx.utils.synth import intent_for

    records = await cp.registry.list_services()
    rng = _random.Random(47)
    n = int(os.environ.get("MCPX_BENCH_PROVENANCE_REQUESTS", "96"))
    rounds = 3
    concurrency = min(engine.config.engine.max_batch_size, 16)
    base_pool = [f"{intent_for(records, rng)} [prv{i}]" for i in range(8)]
    tracer = Tracer(enabled=True, sample_rate=1.0, ring_size=max(1024, n))

    async def _idle() -> None:
        while engine._slab.n_active or engine._queue.qsize():
            await asyncio.sleep(0.05)
        await asyncio.sleep(0.1)

    tag = {"n": 0}

    async def one_round(recorder) -> "tuple[float, list]":
        # Fresh cache-busted intents per round: every round pays the same
        # plan/prefill/decode work whatever ran before it.
        tag["n"] += 1
        intents = [
            f"{base_pool[i % len(base_pool)]} r{tag['n']}-{i}" for i in range(n)
        ]
        await _idle()
        sem = asyncio.Semaphore(concurrency)
        recs: list = []

        async def one(intent: str) -> None:
            async with sem:
                root = tracer.start_request("/plan", method="POST")
                token = prov_mod.begin(recorder)
                err = False
                try:
                    with tracing.activate(root):
                        await cp.plan(intent, use_cache=False)
                except Exception:  # noqa: BLE001 - a failed plan still finishes its trace
                    err = True
                finally:
                    prov_mod.end(token)
                    tracer.finish(root, error=err)
                recs.append(root.record)

        t0 = time.monotonic()
        await asyncio.gather(*(one(i) for i in intents))
        await _idle()
        return n / max(1e-9, time.monotonic() - t0), recs

    off_rates: list[float] = []
    on_rates: list[float] = []
    on_records: list = []
    recorder = ProvenanceRecorder(
        cp.config.telemetry.provenance, metrics=cp.metrics
    )
    for _ in range(rounds):
        # OFF: the default pass-through — no trail ever begins.
        rate, _ = await one_round(None)
        off_rates.append(rate)
        # ON: per-request trail + decision spans + counters.
        rate, recs = await one_round(recorder)
        on_rates.append(rate)
        on_records = recs
    explanations = [build_explanation(r) for r in on_records]
    covered = [
        e for e in explanations
        if not validate_explanation(e)
        and any(d["layer"] == "plan" for d in e["decisions"])
    ]
    decisions_per_request = (
        sum(len(e["decisions"]) for e in explanations) / max(1, len(explanations))
    )
    best_off, best_on = max(off_rates), max(on_rates)
    return {
        "requests": n,
        "rounds": rounds,
        "plans_per_sec_off": round(best_off, 2),
        "plans_per_sec_on": round(best_on, 2),
        # The acceptance number: fractional headline cost of recording
        # every decision (negative = measurement noise).
        "provenance_overhead_frac": round(
            1.0 - best_on / max(1e-9, best_off), 4
        ),
        # Fraction of ON-arm requests whose /explain output is
        # schema-valid and names the plan-origin decision.
        "explanation_coverage": round(
            len(covered) / max(1, len(explanations)), 4
        ),
        "decisions_per_request": round(decisions_per_request, 2),
        "records_emitted": recorder.records_emitted,
    }


async def _run(model_size: str, n_requests: int, concurrency: int, n_services: int) -> dict:
    from aiohttp import ClientSession, TCPConnector
    from aiohttp.test_utils import TestServer

    from mcpx.server.app import build_app
    from mcpx.server.factory import build_control_plane
    from mcpx.utils.synth import synth_registry

    import random

    cfg = _build_config(model_size)
    if not _on_tpu():
        if _pallas_on():
            # ISSUE 15 headline contract: the CPU proxy serves the ragged
            # kernel through the Pallas interpreter (same kernel body TPUs
            # run) instead of silently swapping in the jnp reference —
            # `pallas=true` now means kernel-on-every-path on BOTH
            # platforms. MCPX_BENCH_PALLAS=0 restores the jnp proxy.
            cfg.engine.interpret = True
        else:
            cfg.engine.use_pallas = False
    cp = build_control_plane(cfg)
    # MCPX_BENCH_REGISTRY=ood swaps in the disjoint camelCase naming
    # universe (utils/synth.synth_registry_ood) — the registry the BPE
    # vocab was NOT fitted to, reported alongside the headline so fitted
    # compression can't overstate real-registry performance (VERDICT r4
    # weak #3).
    registry_mode = os.environ.get("MCPX_BENCH_REGISTRY", "synthetic")
    if registry_mode == "ood":
        from mcpx.utils.synth import synth_registry_ood

        records_in = synth_registry_ood(n_services, seed=7)
    elif registry_mode == "synthetic":
        records_in = synth_registry(n_services, seed=7)
    else:
        raise ValueError(
            f"MCPX_BENCH_REGISTRY={registry_mode!r}: expected synthetic|ood"
        )
    for rec in records_in:
        await cp.registry.put(rec)

    app = build_app(cp)
    server = TestServer(app)
    await server.start_server()
    try:
        base = f"http://{server.host}:{server.port}"

        rng = random.Random(11)
        from mcpx.utils.synth import intent_for

        records = await cp.registry.list_services()
        n_lat = int(os.environ.get("MCPX_BENCH_LATENCY_REQUESTS", "192"))
        # Repeat-intent mode (SURVEY §5 plan-cache lever, VERDICT r4 next #8):
        # MCPX_BENCH_UNIQUE_INTENTS=N draws the workload from a pool of N
        # unique intents (expected cache hit share ≈ 1 - N/requests). Default 0
        # = every request unique, which cache-busts by construction — the
        # headline number stays an engine measurement, never a cache one.
        n_unique = int(os.environ.get("MCPX_BENCH_UNIQUE_INTENTS", "0"))
        n_total = n_requests + n_lat
        if n_unique > 0:
            pool = [f"{intent_for(records, rng)} [{i}]" for i in range(n_unique)]
            intents = [pool[i % n_unique] for i in range(n_total)]
        else:
            intents = [f"{intent_for(records, rng)} [{i}]" for i in range(n_total)]

        origins: dict[str, int] = {}

        t_setup0 = time.monotonic()
        async with ClientSession(connector=TCPConnector(limit=concurrency)) as session:
            # Engine bring-up runs as a server background task; wait for
            # /healthz to report ready before the request warmup (this also
            # exercises the warming-state health surface).
            while True:
                async with session.get(f"{base}/healthz") as resp:
                    health = await resp.json()
                if health.get("engine") in ("ready", "n/a", None):
                    break
                if health.get("engine") == "failed":
                    raise RuntimeError(
                        "engine failed during startup: "
                        + health.get("engine_error", "(no detail)")
                    )
                await asyncio.sleep(1.0)

            async def plan_once(intent: str) -> tuple[int, float]:
                t0 = time.monotonic()
                async with session.post(f"{base}/plan", json={"intent": intent}) as resp:
                    body = await resp.json()
                    if resp.status == 200:
                        o = body.get("origin", "unknown")
                        origins[o] = origins.get(o, 0) + 1
                    return resp.status, (time.monotonic() - t0) * 1e3

            # Warmup: trigger engine startup + compile for the hot batch buckets.
            warm = [f"warmup intent {i}" for i in range(cfg.engine.max_batch_size)]
            statuses = await asyncio.gather(*(plan_once(w) for w in warm))
            bad = [s for s, _ in statuses if s != 200]
            if bad:
                raise RuntimeError(f"warmup failed: {len(bad)}/{len(warm)} non-200 responses")
            warmup_s = time.monotonic() - t_setup0
            origins.clear()

            async def get_costs():
                # Roofline cost observatory scrape (GET /costs): XLA-derived
                # executed-work totals whose phase deltas become the output
                # JSON's roofline block. Best-effort — a failed scrape
                # degrades the block to basis="unavailable", never the run.
                try:
                    async with session.get(f"{base}/costs") as resp:
                        return await resp.json()
                except Exception:  # noqa: BLE001 - accounting must not fail the bench
                    return None

            async with session.get(f"{base}/metrics") as resp:
                prom0 = _parse_prom(await resp.text())
            costs0 = await get_costs()

            # ---- Phase 1: closed-loop saturation -> plans/sec
            sat_lat: list[float] = []
            errors = 0
            sem = asyncio.Semaphore(concurrency)

            async def one_sat(intent: str) -> None:
                nonlocal errors
                async with sem:
                    status, ms = await plan_once(intent)
                    if status != 200:
                        errors += 1
                    sat_lat.append(ms)

            t0 = time.monotonic()
            await asyncio.gather(*(one_sat(i) for i in intents[:n_requests]))
            elapsed = time.monotonic() - t0
            plans_per_sec = n_requests / elapsed

            async with session.get(f"{base}/metrics") as resp:
                prom1 = _parse_prom(await resp.text())
            costs1 = await get_costs()

            # ---- Phase 2: open-loop latency at a fraction of measured throughput
            rate_frac = float(os.environ.get("MCPX_BENCH_RATE_FRACTION", "0.7"))
            rate = max(0.5, plans_per_sec * rate_frac)
            open_lat: list[float] = []

            async def one_open(intent: str, delay: float) -> None:
                nonlocal errors
                await asyncio.sleep(delay)
                status, ms = await plan_once(intent)
                if status != 200:
                    errors += 1
                open_lat.append(ms)

            t_open0 = time.monotonic()
            await asyncio.gather(
                *(
                    one_open(intent, i / rate)
                    for i, intent in enumerate(intents[n_requests:])
                )
            )
            open_elapsed = time.monotonic() - t_open0

            # Open-loop phase scrape: the phase split that matters for the p50
            # target is THIS phase's (queue under Little's law in the closed
            # loop says nothing about engine latency — the same reason p50_ms
            # and sat_p50_ms are separate headline fields).
            async with session.get(f"{base}/metrics") as resp:
                prom2 = _parse_prom(await resp.text())
            costs2 = await get_costs()

        # ---- Quality sample: are served plans on-intent? (VERDICT r3 weak #4)
        # A separate small loop AFTER the timed phases so per-response scoring
        # can't contaminate throughput/latency numbers. Random-weight models
        # score near the registry base rate here; trained checkpoints high.
        from mcpx.planner.quality import mean_quality, plan_quality

        by_name = {r.name: r for r in records}
        q_rows = []
        q_origins: dict[str, int] = {}
        async with ClientSession() as session:
            for i in range(32):
                intent = intent_for(records, rng)
                async with session.post(f"{base}/plan", json={"intent": intent}) as resp:
                    if resp.status != 200:
                        continue
                    body = await resp.json()
                    o = body.get("origin", "unknown")
                    q_origins[o] = q_origins.get(o, 0) + 1
                    q_rows.append(plan_quality(body.get("graph") or {}, intent, by_name))
        quality = mean_quality(q_rows)
        # Heuristic fallbacks would inflate the MODEL's apparent quality — the
        # share is reported so a degenerate sample is visible, like the timed
        # phases' llm_share gate.
        quality["llm_share"] = q_origins.get("llm", 0) / max(1, sum(q_origins.values()))

        # End-of-run scrape: grammar_fallback must cover EVERY build this
        # process ran (warmup before prom0, both timed phases, the quality
        # sample after prom1) — a build that degraded anywhere in the run means
        # some reported number was served by a degraded grammar.
        async with ClientSession() as session:
            async with session.get(f"{base}/metrics") as resp:
                prom_end = _parse_prom(await resp.text())

        # ---- Phase 3: scheduler overload (mcpx/scheduler/) — after every
        # headline scrape so attaching the scheduler cannot perturb them.
        overload = await _overload_phase(cp, base, records, rng, plans_per_sec)

        # ---- Phase 4: heterogeneous mixed-traffic (ISSUE 3) — after every
        # headline scrape, so flipping hetero_batch on the live engine
        # can't touch any earlier number.
        mixed = await _mixed_phase(cp, overload)

        # ---- Phase 7: grammar-aware speculative decoding (ISSUE 6) —
        # right after the mixed phase (same flag-flipping discipline, same
        # direct-engine measurement style; numbered 7 by birth order).
        spec = await _spec_phase(cp)

        # ---- Phase 8: radix prefix KV reuse (ISSUE 8) — after every
        # headline scrape (it flips engine.prefix_cache live and drives
        # repeat-intent plans through the serving engine).
        prefix = await _prefix_phase(cp)

        # ---- Phase 9: tiered KV cache (ISSUE 11) — dedicated small
        # engines (working set >= 10x the resident cap, thrash tenant,
        # warm restart, spill chaos); the serving engine sits idle, so
        # the shared metric deltas are the tier engines' alone.
        tier = await _tier_phase(cp)

        # ---- Phase 10: flight recorder + worker-loop profiler (ISSUE 13)
        # — after every headline scrape (it attaches a profiler to the
        # LIVE engine worker and runs a recorder task, which no headline
        # number may see; both detached in its finally).
        flight = await _flight_phase(cp)

        # ---- Phase 11: cost ledger + usage attribution (ISSUE 14) —
        # same live-attach discipline as the flight phase (it flips
        # telemetry.ledger on the serving engine and attaches a usage
        # ledger + SLO tracker, all restored in its finally).
        ledger = await _ledger_phase(cp)

        # ---- Phase 12: ragged kernel + fused decode dispatch (ISSUE 15)
        # — dedicated 1×1 engines (per-step vs fused cadence at the same
        # offered load, kernel-vs-jnp interpret-parity gate); the serving
        # engine sits idle, so the shared metric deltas are the kernel
        # engines' alone.
        kernel = await _kernel_phase(cp)

        # ---- Phase 13: cluster scale-out (ISSUE 16) — dedicated pools
        # (router-sim replicas for scaling/failover/affinity, real small
        # engines for the warm-rejoin snapshot arm); the serving engine
        # sits idle throughout.
        cluster = await _cluster_phase(cp)

        # ---- Phase 14: decision provenance (ISSUE 19) — same live-attach
        # discipline as the flight/ledger phases (a recorder + tracer the
        # workload begins/ends per request; nothing mutated on cp, so no
        # restore needed); runs after every headline scrape.
        provenance = await _provenance_phase(cp)

        # ---- Phase 5: latency attribution (ISSUE 4) — a traced open-loop
        # sample at the phase-2 rate; runs after every headline scrape
        # because attaching the tracer is the one thing this phase does
        # that others must not see.
        attribution = await _attribution_phase(cp, base, records, rng, rate)

        # ---- Phase 6: chaos resilience (ISSUE 5) — dead last: it swaps the
        # orchestrator's transport for a fault injector, which no other
        # phase may ever see (restored in its own finally).
        chaos = await _chaos_phase(cp, base)

    finally:
        # Teardown in a FINALLY: a cancelled run (MCPX_BENCH_RUN_TIMEOUT_S
        # hang-guard) must not leak the engine HBM + TestServer into the
        # in-process model=test fallback retry. Each step is itself bounded
        # and best-effort: teardown of a wedged engine must not become a
        # second hang.
        import contextlib

        with contextlib.suppress(Exception):
            await asyncio.wait_for(server.close(), 30)
        # aclose() whatever the state: a run-timeout can land mid-BRING-UP
        # (2b startup alone is ~167 s), and a "warming" engine's worker
        # thread holds weights+KV in HBM just as much as a ready one's.
        # aclose is state-agnostic (signals the worker, joins bounded,
        # drops device buffers); on a cold engine it is a cheap no-op.
        engine = getattr(cp.planner, "engine", None)
        if engine is not None and engine.state != "closed":
            with contextlib.suppress(Exception):
                await asyncio.wait_for(engine.aclose(), 30)

    if errors > max(1, (n_requests + n_lat) // 100):
        raise BenchGateError(f"{errors}/{n_requests + n_lat} requests failed")
    total_plans = sum(origins.values())
    llm_share = origins.get("llm", 0) / max(1, total_plans)
    if llm_share < 0.95:
        raise BenchGateError(
            f"llm_share={llm_share:.3f} < 0.95 (origins={origins}): most plans "
            "fell back to the heuristic — the bench would be measuring the "
            "fallback path, not the engine"
        )

    # ---- engine-side numbers for phase 1 (deltas across the timed region)
    def delta(name: str) -> float:
        return prom1.get(name, 0.0) - prom0.get(name, 0.0)

    decode_tokens = delta("mcpx_engine_decode_tokens_total")
    decode_forwards = delta("mcpx_engine_decode_forwards_total")
    prefill_tokens = delta("mcpx_engine_prefill_tokens_total")
    model_cfg = getattr(engine, "model_cfg", None)
    n_params = model_cfg.n_params if model_cfg is not None else 0
    # Analytic goodput-FLOPs model: 2 · params per token processed
    # (prefill + decode), PLUS the speculative drafter's scoring matmuls
    # when the headline served with speculation on (2·D·V per drafted
    # token — drafter_flops_per_token) so a speculated run bills its
    # drafter honestly instead of flattering MFU with free proposals.
    drafted_hdr = delta('mcpx_engine_spec_drafted_total{cls="constrained"}') + delta(
        'mcpx_engine_spec_drafted_total{cls="free"}'
    )
    model_flops = 2.0 * n_params * (prefill_tokens + decode_tokens)
    if drafted_hdr and model_cfg is not None:
        from mcpx.engine.speculative import drafter_flops_per_token

        model_flops += drafted_hdr * drafter_flops_per_token(
            model_cfg.d_model, engine.tokenizer.vocab_size
        )
    goodput_flops = model_flops / max(1e-9, elapsed)
    peak = _peak_flops_per_chip() if _on_tpu() else None
    import jax

    # The engine spans every visible chip by default (auto mesh), so the
    # peak is per-chip x chips actually meshed.
    n_chips = (
        engine._mesh.devices.size
        if engine is not None and engine._mesh is not None
        else len(jax.devices())
    )
    if peak is not None:
        peak_flops_total = peak * n_chips
        peak_flops_basis = "datasheet"
    else:
        # Unknown hardware / CPU proxy: no datasheet peak, but a null MFU
        # hides whether a change moved achieved FLOPs at all (the honest-
        # progress prerequisite for the ragged-kernel roadmap item). Use a
        # MEASURED dense-matmul peak of this backend as the denominator —
        # labeled "measured_matmul" so the number is never read as a
        # datasheet fraction. One host = one "chip" here (the virtual CPU
        # mesh shares the same silicon).
        peak_flops_total = max(1.0, _measured_peak_flops())
        peak_flops_basis = "measured_matmul"
    mfu_analytic = goodput_flops / peak_flops_total
    # HBM bandwidth peak: datasheet only (no honest CPU-proxy equivalent).
    peak_bytes_total = None
    if _on_tpu():
        try:
            from mcpx.telemetry.costs import device_peaks

            pk = device_peaks()
            if pk.get("hbm_bytes_s_per_chip"):
                peak_bytes_total = pk["hbm_bytes_s_per_chip"] * n_chips
        except Exception:  # noqa: BLE001 - peaks are telemetry, never fatal
            pass
    # Roofline block (ISSUE 7 tentpole): the headline MFU is XLA-derived
    # (cost_analysis totals over the timed phase) wherever the backend
    # publishes costs; the analytic 2·params·tokens model stays as a
    # cross-check inside the block (xla_vs_analytic divergence).
    roofline_block = _roofline_block(
        costs0, costs1, costs2, elapsed, open_elapsed,
        peak_flops_total, peak_flops_basis, peak_bytes_total,
        mfu_analytic=mfu_analytic, analytic_flops=model_flops,
    )
    sat_rl = (roofline_block.get("phases") or {}).get("sat")
    if sat_rl is not None and sat_rl.get("mfu") is not None:
        mfu = sat_rl["mfu"]
        mfu_basis = "xla_cost_analysis"
    else:
        # Labeled fallback: the pre-observatory analytic path, with its
        # round-comparable basis labels.
        mfu = mfu_analytic
        mfu_basis = "datasheet" if peak is not None else "measured_matmul"

    sat_sorted = sorted(sat_lat)
    open_sorted = sorted(open_lat) or [float("nan")]  # latency phase may be skipped
    import jax

    return {
        "backend": jax.default_backend(),
        # Echoed into the output JSON by _output_json: the values this run
        # ACTUALLY used (n_services is a regression-report scenario key —
        # re-deriving it from env defaults there could mis-bucket the run).
        "n_services": n_services,
        "n_requests": n_requests,
        # Scheduler overload scenario (None when skipped): shed-rate,
        # degraded-share, admitted p50 vs the configured SLO at >= 4x the
        # measured sustainable rate.
        "overload": overload,
        # Heterogeneous mixed-traffic scenario (None when skipped):
        # mixed_plans_per_sec hetero vs drain at the same offered load,
        # head-of-line wait p99, degraded_share.
        "mixed": mixed,
        # Speculative-decoding scenario (None when skipped): the same
        # mixed stream served with speculation off (true per-token
        # baseline) vs on — decode tok/s per mode, the speedup, per-class
        # accept rates, and the greedy byte-parity verdict.
        "spec": spec,
        # Radix prefix KV reuse scenario (None when skipped): prefill
        # tokens/request and replan p50 with the prefix cache off vs on
        # over a repeat-heavy intent stream at the same offered load.
        "prefix": prefix,
        # Tiered KV cache scenario (None when skipped): token-hit-rate
        # retention tiered vs single-tier at a working set >= 10x the
        # resident cap, per-tenant isolation under adversarial thrash,
        # warm-restart first-plan prefill, spill-chaos degradation.
        "tier": tier,
        # Flight recorder + worker-loop profiler scenario (None when
        # skipped): recorder+profiler overhead vs the pass-through, and
        # the worker thread's wall time attributed to named phases.
        "flight": flight,
        # Cost ledger + usage attribution scenario (None when skipped):
        # billing overhead vs the pass-through, per-tenant itemized
        # usage, wall-attribution fraction, FLOP conservation verdict.
        "ledger": ledger,
        # Ragged kernel + fused dispatch scenario (None when skipped):
        # per-step vs fused decode dispatch cadence at the same offered
        # load, dispatch-per-token drop, wall-clock guard, and the
        # kernel-vs-jnp interpret-parity verdict.
        "kernel": kernel,
        # Cluster scale-out scenario (None when skipped): plans/s at
        # 1/2/4 replicas through the real router (router-sim basis), p99
        # with one replica killed mid-phase, routed-vs-round-robin prefix
        # token hit rate, and the warm-rejoin KV-snapshot prefill ratio.
        "cluster": cluster,
        # Decision-provenance scenario (None when skipped): recorder
        # overhead vs the pass-through, /explain schema coverage, and
        # decisions recorded per request.
        "provenance": provenance,
        # Per-phase latency attribution from sampled request traces (None
        # when skipped): p50/p99 of scheduler-queue vs engine admit-wait vs
        # prefill vs decode vs tool fan-out, plus each phase's share of the
        # p50 request — BENCH_*.json explains regressions, not just
        # reports them.
        "latency_attribution": attribution,
        # Chaos resilience scenario (None when skipped): /execute success
        # rate and deadline-overrun share under the same seeded fault
        # profile with resilience on vs off (mcpx/resilience/).
        "chaos": chaos,
        "plan_quality": quality,
        "plans_per_sec": plans_per_sec,
        "p50_ms": statistics.median(open_sorted),
        "p99_ms": open_sorted[int(0.99 * (len(open_sorted) - 1))],
        "open_loop_rate": rate,
        "sat_p50_ms": statistics.median(sat_sorted),
        "sat_p99_ms": sat_sorted[int(0.99 * (len(sat_sorted) - 1))],
        "elapsed_s": elapsed,
        "warmup_s": warmup_s,
        "errors": errors,
        "llm_share": llm_share,
        "decode_tok_s": decode_tokens / max(1e-9, elapsed),
        "decode_forwards": decode_forwards,
        "tok_per_forward": decode_tokens / max(1.0, decode_forwards),
        # Per-phase achieved tokens per model forward — the speculation
        # amortisation split by phase (saturation vs open-loop), so a
        # regression in either regime is attributable.
        "phase_tok_per_forward": {
            "sat": round(decode_tokens / max(1.0, decode_forwards), 2),
            "open": round(
                (prom2.get("mcpx_engine_decode_tokens_total", 0.0)
                 - prom1.get("mcpx_engine_decode_tokens_total", 0.0))
                / max(
                    1.0,
                    prom2.get("mcpx_engine_decode_forwards_total", 0.0)
                    - prom1.get("mcpx_engine_decode_forwards_total", 0.0),
                ),
                2,
            ),
        },
        "prefill_tokens": prefill_tokens,
        "mfu": mfu,
        "mfu_basis": mfu_basis,
        # Per-phase XLA roofline (achieved FLOP/s, bytes/s, arithmetic
        # intensity, position vs device peaks) + analytic cross-check —
        # basis="unavailable" when the backend publishes no costs.
        "roofline": roofline_block,
        # Why the Pallas kernel path is (not) serving, readable from the
        # JSON alone — platform / operator override / smoke evidence /
        # engine hardware probe. pallas_effective is the engine's RESOLVED
        # kernel path (the probe's verdict), which the output's `pallas`
        # flag reports so flag and reason can never contradict.
        "pallas_reason": _pallas_reason(getattr(engine, "_use_pallas", None)),
        "pallas_effective": (
            bool(engine._use_pallas)
            if engine is not None and getattr(engine, "_use_pallas", None) is not None
            else None
        ),
        # Per-path engagement (ISSUE 15): decode / suffix-prefill /
        # spec-verify each report kernel-routed-or-not + dispatch counts
        # + the blocking reason — a headline `pallas=true` can no longer
        # mask a single path's jnp fork.
        "pallas_paths": (
            engine.pallas_paths()
            if engine is not None and hasattr(engine, "pallas_paths")
            else None
        ),
        # Plan-cache accounting for repeat-intent runs (hit share over the
        # timed phase; 0.0 in the default cache-busting workload).
        "cache_hit_share": (
            (delta('mcpx_plan_cache_total{result="hit"}')
             + delta('mcpx_plan_cache_total{result="redis_hit"}'))
            / max(1.0, n_requests)
        ),
        "unique_intents": n_unique,
        # Honesty field (VERDICT r4 weak #5): nonzero means grammar builds
        # degraded during this run — "shape_only" drops the registry-name
        # guarantee entirely, "keys_free" just loses key tries/speculation.
        # Absolute end-of-run totals (prom_end, not prom1): builds happen at
        # warmup (before prom0) and in the quality sample (after prom1) too,
        # and a degraded build ANYWHERE in the run taints what was served.
        # Kinds enumerated dynamically so a new degradation kind (e.g. the
        # typed_off size-gate) can never be minted in the planner yet stay
        # invisible in the one JSON line the operator reads; the canonical
        # kinds are pre-seeded so "zero fallbacks" is an explicit 0, not an
        # absent key.
        "grammar_fallback": {
            **{k: 0 for k in ("shape_only", "keys_free", "typed_off")},
            **_fallback_kinds(prom_end),
        },
        # Saturation-phase split: queue here is Little's-law backlog at
        # 256-way concurrency — read it with sat_p50_ms, not p50_ms.
        "phase_p50_ms": {
            "queue": _hist_p50(prom1, "mcpx_engine_queue_seconds", prom0),
            "prefill": _hist_p50(prom1, "mcpx_engine_prefill_seconds", prom0),
            "decode": _hist_p50(prom1, "mcpx_engine_decode_seconds", prom0),
        },
        # Open-loop split: the decomposition of p50_ms — the phase the
        # <150 ms north-star target is scored on.
        "phase_p50_open_ms": {
            "queue": _hist_p50(prom2, "mcpx_engine_queue_seconds", prom1),
            "prefill": _hist_p50(prom2, "mcpx_engine_prefill_seconds", prom1),
            "decode": _hist_p50(prom2, "mcpx_engine_decode_seconds", prom1),
        },
    }


def _smoke_artifact() -> dict:
    """benchmarks/smoke_tpu.json if present and ok, else {} — the last
    hardware-PROVEN 2b bring-up config (batch, pallas)."""
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks", "smoke_tpu.json"
    )
    try:
        with open(path) as f:
            d = json.load(f)
        return d if d.get("ok") else {}
    except Exception:  # noqa: BLE001 - absent/garbled artifact = no evidence
        return {}


def _serving_announced(batch: int, source: str, tag: str = "bench") -> int:
    """Single owner of the serving-config announcement: one stderr line per
    effective-config CHANGE (repeats fold; a sweep's per-entry overrides
    each appear), in EVERY entrypoint's log and on every resolution path
    (env, smoke artifact, default), recording the batch + kernel path — what
    steered a run must be readable off the run itself, never inferred from
    defaults, and _pallas_on() here folds in any MCPX_BENCH_PALLAS override
    so the line matches what was actually served. Returns ``batch`` so call
    sites can announce at the point of resolution."""
    key = (tag, batch, source, _pallas_on())
    if getattr(_serving_announced, "_last", None) != key:
        _serving_announced._last = key
        # De-dup on the CONFIG, not once-per-process: a probe sweep serves
        # several batches in one process, and each change must appear in
        # the log — only repeats of the same effective config are folded.
        print(
            f"{tag}: serving batch={batch} ({source}) pallas={_pallas_on()}",
            file=sys.stderr,
        )
    return batch


def _bench_batch(model_size: str) -> int:
    """Engine batch: env override > smoke-proven value (2b only) > 64.
    The 2b fallback without smoke evidence is 32: the only measured batch-64
    attempt hung its first generate and took the relay down with it — on the
    driver's unattended round-end run, a conservative batch that SERVES
    beats an aggressive one that wedges. keep_if_json deliberately preserves
    a previous session's smoke across a failed one, so every path announces
    via _serving_announced (and the served batch/kernel are fields of the
    output JSON)."""
    env = os.environ.get("MCPX_BENCH_BATCH")
    if env:
        return _serving_announced(int(env), "env MCPX_BENCH_BATCH")
    if model_size == "2b":
        proven = _smoke_artifact().get("batch")
        if proven:
            return _serving_announced(int(proven), "benchmarks/smoke_tpu.json")
        return _serving_announced(32, "2b conservative default")
    return _serving_announced(64, "default")


def _fallback_kinds(prom: dict[str, float]) -> dict[str, float]:
    """Totals per ``kind`` label of mcpx_grammar_fallbacks_total."""
    out: dict[str, float] = {}
    for k, v in prom.items():
        if k.startswith("mcpx_grammar_fallbacks_total"):
            m = re.search(r'kind="([^"]+)"', k)
            if m:
                out[m.group(1)] = out.get(m.group(1), 0.0) + v
    return out


def _pallas_on() -> bool:
    """Whether the ragged kernel path serves. MCPX_BENCH_PALLAS overrides
    explicitly; on TPU the smoke artifact's proven kernel config applies
    (a smoke that only served fused-jnp must steer the driver's unattended
    round-end run too); OFF-TPU the kernel serves by default through the
    Pallas INTERPRETER (ISSUE 15: the CPU proxy runs the same kernel body
    TPUs run — engine.interpret is set by _run), so the headline `pallas`
    flag finally means the same thing on both platforms."""
    env = os.environ.get("MCPX_BENCH_PALLAS")
    if env is not None:
        return env != "0"
    if not _on_tpu():
        return True
    return bool(_smoke_artifact().get("pallas", True))


def _measurement_basis() -> str:
    """The run's measurement basis (ROADMAP item 4), as a first-class
    scenario dimension: ``real-TPU`` (Mosaic kernels on hardware),
    ``interpret-kernel`` (CPU proxy serving the same kernel body through
    the Pallas interpreter — the r09 default), or ``jnp-proxy`` (the
    fused-jnp reference, MCPX_BENCH_PALLAS=0 off-TPU). `mcpx bench
    report` keys scenarios on this, so a basis change reads as a NEW
    series, not a regression."""
    if _on_tpu():
        return "real-TPU"
    return "interpret-kernel" if _pallas_on() else "jnp-proxy"


def _pallas_reason(engine_use_pallas: "bool | None" = None) -> str:
    """WHY the headline serves (or doesn't serve) the Pallas paged-attention
    kernel, so ``pallas=false`` is diagnosable from the output JSON alone:
    platform, operator override, smoke-artifact evidence, or the engine's
    own hardware probe (``engine_use_pallas`` = the live engine's resolved
    ``_use_pallas``, when available)."""
    env = os.environ.get("MCPX_BENCH_PALLAS")
    if env == "0":
        return "MCPX_BENCH_PALLAS=0: operator forced the fused-jnp path"
    if not _on_tpu():
        return (
            "enabled (interpret): cpu proxy serves the ragged kernel "
            "through the Pallas interpreter — the same kernel body TPUs "
            "run; Mosaic lowering itself needs TPU hardware"
        )
    if env is None and not _smoke_artifact().get("pallas", True):
        return (
            "benchmarks/smoke_tpu.json: the last hardware-proven bring-up "
            "served fused-jnp only"
        )
    if engine_use_pallas is False:
        return (
            "engine probe: head_dim % 128 != 0 — Mosaic lane tiling rejects "
            "the paged kernel on hardware (fused-jnp served)"
        )
    return "enabled"


def _on_tpu() -> bool:
    import jax

    return jax.default_backend() not in ("cpu",)


def _device_guard() -> None:
    """Probe device availability in a SUBPROCESS with a timeout before this
    process touches JAX. The axon tunnel's failure mode when the TPU server
    holds a dead session is a silent in-process HANG inside make_c_api_client
    (uninterruptible once entered), not an exception — observed after a
    device-OOM crash wedged the relay for hours. A degraded CPU bench line
    beats a driver-killing hang."""
    timeout_s = float(os.environ.get("MCPX_BENCH_DEVICE_TIMEOUT_S", "120"))
    try:
        # The Popen/bounded-poll/abandon pattern (and its rationale: no
        # pipes, never wait on a possibly-D-state child) lives in ONE
        # place — benchmarks/tunnel_probe.py.
        sys.path.insert(
            0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks")
        )
        from tunnel_probe import probe

        if not probe(timeout_s):
            raise TimeoutError(f"device probe failed/exceeded {timeout_s}s")
        return
    except Exception as e:  # noqa: BLE001 - any probe failure -> CPU fallback
        print(
            f"bench: device probe failed ({type(e).__name__}); falling back to "
            "an 8-device virtual CPU platform (model=test) — NOT a TPU number",
            file=sys.stderr,
        )
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from __graft_entry__ import _force_virtual_cpu

        _force_virtual_cpu(8)
        os.environ.setdefault("MCPX_BENCH_MODEL", "test")
        os.environ.setdefault("MCPX_BENCH_REQUESTS", "64")
        os.environ.setdefault("MCPX_BENCH_CONCURRENCY", "32")
        os.environ.setdefault("MCPX_BENCH_LATENCY_REQUESTS", "24")
        os.environ.setdefault("MCPX_BENCH_OVERLOAD_REQUESTS", "64")


def main() -> None:
    _device_guard()
    model = os.environ.get("MCPX_BENCH_MODEL")
    n_requests = int(os.environ.get("MCPX_BENCH_REQUESTS", "512"))
    concurrency = int(os.environ.get("MCPX_BENCH_CONCURRENCY", "256"))
    n_services = int(os.environ.get("MCPX_BENCH_SERVICES", "1000"))
    if model is None:
        model = "2b" if _on_tpu() else "test"

    # Bounded: the measured batch-64 failure mode is a generate that never
    # resolves (worker thread stuck in a device call) — an exception clause
    # cannot catch a hang, but wait_for regains control because the stuck
    # call lives in the engine's worker THREAD, not this event loop. The
    # driver's unattended round-end run must always terminate. ONE deadline
    # covers both attempts: a fresh budget for the fallback tier would let
    # worst-case runtime (2x) blow through the session script's step timeout
    # and lose the artifact anyway.
    run_deadline = time.monotonic() + float(
        os.environ.get("MCPX_BENCH_RUN_TIMEOUT_S", "2400")
    )

    def _run_bounded(m: str):
        budget = max(120.0, run_deadline - time.monotonic())

        async def go():
            return await asyncio.wait_for(
                _run(m, n_requests, concurrency, n_services), budget
            )

        return asyncio.run(go())

    try:
        stats = _run_bounded(model)
    except BenchGateError:
        raise  # honesty gate: a degenerate run must fail, not retry smaller
    except Exception as e:  # noqa: BLE001 - one fallback tier, then report
        print(f"bench: model={model} failed ({type(e).__name__}: {e}); retrying size=test",
              file=sys.stderr)
        model = "test"
        stats = _run_bounded(model)

    # Bounded so a second engine bring-up can never hang the process past
    # the session script's step timeout and discard the already-measured
    # headline (the wedge failure mode is a silent in-process hang the
    # except-clause cannot catch; wait_for returns control even then).
    q_timeout = float(os.environ.get("MCPX_BENCH_QUALITY_TIMEOUT_S", "1800"))

    async def _quality_bounded():
        # The deadline lets tier 2 self-clamp so the outer hang-guard never
        # cancels mid-tier2 and discards the measured tier-1 row.
        deadline = time.monotonic() + q_timeout
        return await asyncio.wait_for(
            _run_quality_trained(deadline=deadline), q_timeout
        )

    if os.environ.get("MCPX_BENCH_SKIP_QUALITY") == "1":
        # Auxiliary rows (OOD/cache/SP) skip the phase cleanly: a timeout
        # mid-bring-up would abandon a warming engine that keeps holding
        # device memory into the session's NEXT bench run.
        quality_trained = {"skipped": True}
    else:
        try:
            quality_trained = asyncio.run(_quality_bounded())
        except Exception as e:  # noqa: BLE001 - must not kill the bench
            print(f"bench: trained-quality phase failed ({type(e).__name__}: {e})",
                  file=sys.stderr)
            quality_trained = {"error": f"{type(e).__name__}: {e}"}

    print(json.dumps(_output_json(stats, quality_trained, model)))


def _regression_block(out: dict) -> dict:
    """The scenario-keyed regression verdict of THIS run against the
    committed BENCH_r*.json series (mcpx/cli/bench_report.py — the same
    report ``mcpx bench report`` computes offline), embedded so each new
    artifact carries its own verdict."""
    try:
        from mcpx.cli.bench_report import build_report, default_series, load_runs

        series = load_runs(
            default_series(os.path.dirname(os.path.abspath(__file__)))
        )
        return build_report(series, current=out)
    except Exception as e:  # noqa: BLE001 - the verdict must never kill the artifact
        return {"verdict": "error", "error": f"{type(e).__name__}: {e}"}


def _output_json(stats: dict, quality_trained, model: str) -> dict:
    """The one JSON line the bench prints — schema-gated by
    tests/test_bench_schema.py so later PRs can't silently drop fields
    (roofline block, pallas_reason, regression verdict included)."""
    value = round(stats["plans_per_sec"], 2)
    out = {
                "metric": "plans_per_sec",
                "value": value,
                "unit": "plans/s",
                "vs_baseline": round(value / 100.0, 3),
                "p50_ms": round(stats["p50_ms"], 1),
                "p99_ms": round(stats["p99_ms"], 1),
                "open_loop_rate": round(stats["open_loop_rate"], 2),
                "sat_p50_ms": round(stats["sat_p50_ms"], 1),
                "sat_p99_ms": round(stats["sat_p99_ms"], 1),
                "llm_share": round(stats["llm_share"], 4),
                "decode_tok_s": round(stats["decode_tok_s"], 1),
                "decode_forwards": int(stats["decode_forwards"]),
                "tok_per_forward": round(stats["tok_per_forward"], 2),
                "prefill_tokens": int(stats["prefill_tokens"]),
                "mfu": round(stats["mfu"], 4) if stats["mfu"] is not None else None,
                "mfu_basis": stats["mfu_basis"],
                "phase_tok_per_forward": stats["phase_tok_per_forward"],
                "phase_p50_ms": {
                    k: round(v, 1) for k, v in stats["phase_p50_ms"].items()
                },
                "phase_p50_open_ms": {
                    k: round(v, 1) for k, v in stats["phase_p50_open_ms"].items()
                },
                # Intent-match quality of the headline run's plans (random
                # weights score near base rate) and of the committed trained
                # checkpoint served through the same stack (null when no
                # artifact is committed).
                "plan_quality": {
                    k: round(v, 3) for k, v in stats["plan_quality"].items()
                },
                "plan_quality_trained": (
                    {k: (round(v, 3) if isinstance(v, float) else v)
                     for k, v in quality_trained.items()}
                    if isinstance(quality_trained, dict) else None
                ),
                "model": model,
                "batch": _bench_batch(model),
                # The engine's RESOLVED kernel path when known (the
                # head_dim hardware probe can veto a requested Pallas
                # config), else the env/smoke resolution — so the flag
                # can never contradict pallas_reason below.
                "pallas": (
                    bool(stats["pallas_effective"])
                    if stats.get("pallas_effective") is not None
                    else _pallas_on()
                ),
                # Satellite (ISSUE 7): pallas=false is diagnosable from the
                # JSON alone — platform / override / smoke / engine probe.
                "pallas_reason": stats.get("pallas_reason") or _pallas_reason(),
                # Satellite (ISSUE 15): the single boolean above is backed
                # by PER-PATH engagement (decode / suffix-prefill /
                # spec-verify, each with dispatch counts and a blocking
                # reason when jnp-forked) — the block that makes a
                # headline `pallas=true` unable to mask one path's fork.
                "pallas_paths": stats.get("pallas_paths"),
                # Tentpole (ISSUE 7): per-phase XLA roofline + analytic
                # cross-check; basis labels fall back, never vanish.
                "roofline": stats.get("roofline")
                or {"basis": "unavailable", "mfu_basis": "unavailable",
                    "phases": {"sat": None, "open": None}},
                "vocab": os.environ.get("MCPX_BENCH_VOCAB", "bpe"),
                "quantize": os.environ.get("MCPX_BENCH_QUANTIZE", "none"),
                "registry": os.environ.get("MCPX_BENCH_REGISTRY", "synthetic"),
                "backend": stats["backend"],
                # Measurement basis as a first-class scenario dimension
                # (ROADMAP item 4): jnp-proxy / interpret-kernel /
                # real-TPU — `mcpx bench report` refuses to compare runs
                # across a basis change (a measurement change is not a
                # performance change).
                "measurement_basis": _measurement_basis(),
                "n_services": stats["n_services"],
                "requests": stats["n_requests"],
                "errors": stats["errors"],
                "overload": stats["overload"],
                "mixed": stats["mixed"],
                "spec": stats["spec"],
                # Acceptance keys promoted to the top level (ISSUE 6): the
                # same mixed stream served with speculation off vs on.
                "spec_decode_tok_s": (
                    stats["spec"]["spec_decode_tok_s"] if stats["spec"] else None
                ),
                "spec_speedup": (
                    stats["spec"]["spec_speedup"] if stats["spec"] else None
                ),
                "spec_speedup_basis": (
                    stats["spec"]["spec_speedup_basis"] if stats["spec"] else None
                ),
                "spec_accept_rate": (
                    stats["spec"]["spec_accept_rate"] if stats["spec"] else None
                ),
                "prefix": stats["prefix"],
                # Acceptance keys promoted to the top level (ISSUE 8): the
                # same repeat-heavy stream planned with the radix prefix
                # cache off vs on, plus cold-vs-warm replan p50.
                "prefill_tokens_per_request": (
                    stats["prefix"]["prefill_tokens_per_request"]
                    if stats["prefix"] else None
                ),
                "prefill_reduction": (
                    stats["prefix"]["prefill_reduction"]
                    if stats["prefix"] else None
                ),
                "prefix_hit_rate": (
                    stats["prefix"]["prefix_hit_rate"]
                    if stats["prefix"] else None
                ),
                "replan_p50_cold_ms": (
                    stats["prefix"]["replan_p50_cold_ms"]
                    if stats["prefix"] else None
                ),
                "replan_p50_warm_ms": (
                    stats["prefix"]["replan_p50_warm_ms"]
                    if stats["prefix"] else None
                ),
                # Warm replan p50 AT SATURATION (the r06-surfaced
                # weakness): warm replans timed while background traffic
                # keeps the slab full — tracked so the ragged-kernel and
                # scheduler work can be judged against it.
                "replan_warm_sat_p50_ms": (
                    stats["prefix"].get("replan_warm_sat_p50_ms")
                    if stats["prefix"] else None
                ),
                "tier": stats.get("tier"),
                # Acceptance keys promoted to the top level (ISSUE 11):
                # tiered-vs-single token hit rate at a >=10x working set,
                # the victim tenant's isolation floor, and the
                # warm-restart first-plan prefill ratio.
                "tier_token_hit_rate": (
                    stats["tier"]["tier_token_hit_rate"]
                    if stats.get("tier") else None
                ),
                "tier_hit_ratio": (
                    stats["tier"]["tier_hit_ratio"]
                    if stats.get("tier") else None
                ),
                "victim_token_hit_rate": (
                    stats["tier"]["victim_token_hit_rate"]
                    if stats.get("tier") else None
                ),
                "warm_restart_prefill_ratio": (
                    stats["tier"]["warm_restart_prefill_ratio"]
                    if stats.get("tier") else None
                ),
                "flight": stats.get("flight"),
                # Acceptance keys promoted to the top level (ISSUE 13):
                # the recorder+profiler's fractional headline cost and the
                # worker thread's named-phase wall-time attribution.
                "flight_overhead_frac": (
                    stats["flight"]["flight_overhead_frac"]
                    if stats.get("flight") else None
                ),
                "worker_profile": (
                    stats["flight"]["worker_profile"]
                    if stats.get("flight") else None
                ),
                "kernel": stats.get("kernel"),
                # Acceptance keys promoted to the top level (ISSUE 15):
                # fused-dispatch cadence (decode dispatches per token,
                # with the per-step arm right next to it) and the
                # wall-clock guard (fused tokens/s over per-step).
                "decode_dispatches_per_token": (
                    stats["kernel"]["decode_dispatches_per_token"]
                    if stats.get("kernel") else None
                ),
                "decode_dispatches_per_token_per_step": (
                    stats["kernel"]["decode_dispatches_per_token_per_step"]
                    if stats.get("kernel") else None
                ),
                "fused_decode_speedup": (
                    stats["kernel"]["fused_decode_speedup"]
                    if stats.get("kernel") else None
                ),
                "cluster": stats.get("cluster"),
                # Acceptance keys promoted to the top level (ISSUE 16):
                # plans/s linearity over replicas (router-sim basis), p99
                # with one replica killed mid-phase over the all-up
                # baseline, routed-vs-round-robin prefix token hit rate,
                # and the rejoined replica's warm-restart prefill ratio.
                "cluster_scaling_linearity": (
                    stats["cluster"]["cluster_scaling_linearity"]
                    if stats.get("cluster") else None
                ),
                "cluster_p99_one_down_ratio": (
                    stats["cluster"]["cluster_p99_one_down_ratio"]
                    if stats.get("cluster") else None
                ),
                "cluster_routed_token_hit_rate": (
                    stats["cluster"]["cluster_routed_token_hit_rate"]
                    if stats.get("cluster") else None
                ),
                "cluster_rr_token_hit_rate": (
                    stats["cluster"]["cluster_rr_token_hit_rate"]
                    if stats.get("cluster") else None
                ),
                "cluster_affinity_hit_margin": (
                    stats["cluster"]["cluster_affinity_hit_margin"]
                    if stats.get("cluster") else None
                ),
                "cluster_warm_rejoin_prefill_ratio": (
                    stats["cluster"]["cluster_warm_rejoin_prefill_ratio"]
                    if stats.get("cluster") else None
                ),
                "provenance": stats.get("provenance"),
                # Acceptance keys promoted to the top level (ISSUE 19):
                # the decision recorder's fractional headline cost and
                # the /explain schema-coverage fraction.
                "provenance_overhead_frac": (
                    stats["provenance"]["provenance_overhead_frac"]
                    if stats.get("provenance") else None
                ),
                "explanation_coverage": (
                    stats["provenance"]["explanation_coverage"]
                    if stats.get("provenance") else None
                ),
                "ledger": stats.get("ledger"),
                # Acceptance keys promoted to the top level (ISSUE 14):
                # the cost ledger's fractional headline cost and the
                # per-tenant usage-attribution block (TRACKED_METRICS
                # reads attribution.wall_attributed_frac).
                "ledger_overhead_frac": (
                    stats["ledger"]["ledger_overhead_frac"]
                    if stats.get("ledger") else None
                ),
                "attribution": (
                    stats["ledger"]["attribution"]
                    if stats.get("ledger") else None
                ),
                "latency_attribution": stats["latency_attribution"],
                "chaos": stats["chaos"],
                # Acceptance keys promoted to the top level (ISSUE 5): the
                # same seeded fault profile served with resilience on vs off.
                "chaos_success_rate": (
                    stats["chaos"]["chaos_success_rate"] if stats["chaos"] else None
                ),
                "chaos_success_rate_baseline": (
                    stats["chaos"]["chaos_success_rate_baseline"]
                    if stats["chaos"] else None
                ),
                "deadline_overrun_share": (
                    stats["chaos"]["deadline_overrun_share"]
                    if stats["chaos"] else None
                ),
                "grammar_fallback": stats["grammar_fallback"],
                "cache_hit_share": round(stats["cache_hit_share"], 4),
                "unique_intents": stats["unique_intents"],
    }
    # Regression tracking (ISSUE 7 tentpole): the artifact carries its own
    # verdict against the committed series — appended last so the verdict
    # judges the final field values above.
    out["regression"] = _regression_block(out)
    return out


if __name__ == "__main__":
    main()
